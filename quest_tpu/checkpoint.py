"""Checkpoint / resume: durable snapshots of registers and RNG state.

The reference has no built-in checkpointing (SURVEY.md section 5); its
primitives for rolling your own are ``reportState`` (CSV dump of the local
chunk, QuEST_common.c:219-231) and ``initStateFromAmps``/``setAmps``
(QuEST.c:157-162). This module provides both:

- :func:`saveQureg` / :func:`loadQureg` -- SHARDED binary snapshots: each
  process writes only the shards its own devices hold (one npz per device
  shard + a JSON index), so a pod-scale register checkpoints with zero
  cross-host traffic and per-host memory bounded by its own shards -- at
  the 34q target that is chunk-sized, not 128 GiB. Loads read only the
  shard files overlapping the loading process's devices and re-place them
  under the destination environment's sharding (layout is an execution
  property, not a state property; meshes may differ between save and load).
- :func:`writeStateToCSV` -- the reference's ``reportState`` file format
  (one "re, im" row per amplitude, state_rank_0.csv) for interop.

Write protocol (a partial save is never loadable): existing metadata is
invalidated first, every shard payload lands via atomic rename, processes
synchronise, and only then does process 0 write fresh metadata (also via
rename) naming every shard file.

Verification has real teeth (ISSUE 7): every format-2 shard records the
CRC32 of its raw amplitude payload in the JSON index; loads recompute and
reject mismatches with a QuESTError NAMING the shard. All shard payloads
are assembled and verified BEFORE the destination register is created or
the env RNG touched, so a corrupt, truncated, or mismatched snapshot
raises and leaves everything intact. Shard writes pass through the
``checkpoint.write`` fault-injection site (quest_tpu.resilience), which is
how the corrupted-snapshot tests and tools/chaos.py manufacture torn and
bit-flipped shards.
"""

from __future__ import annotations

import json
import os
import zlib

import jax
import numpy as np

from .environment import QuESTEnv
from .registers import Qureg, createQureg, createDensityQureg
from .validation import QuESTError

__all__ = ["saveQureg", "loadQureg", "verify_snapshot", "writeStateToCSV",
           "saveSeeds", "loadSeeds"]

_META_NAME = "qureg.json"
_AMPS_NAME = "amps.npz"          # format-1 monolithic payload (still loadable)


def _shard_ranges(amps):
    """[(start, stop, host_data)] for this process's addressable shards of
    the (2, num_amps) array, deduplicated (replicated layouts repeat the
    same index on several devices) and amp-axis-contiguous."""
    out = {}
    for sh in amps.addressable_shards:
        idx = sh.index[1] if len(sh.index) > 1 else slice(None)
        start = idx.start or 0
        stop = idx.stop if idx.stop is not None else amps.shape[1]
        if start not in out:
            out[start] = (stop, sh.data)
    return [(start, stop, data)
            for start, (stop, data) in sorted(out.items())]


def saveQureg(qureg: Qureg, directory: str) -> None:
    """Snapshot ``qureg`` (amplitudes + structure + env RNG position) into
    ``directory`` (created if needed). Sharded write: every process writes
    exactly the shards its devices hold -- no gather, no cross-host
    traffic (the round-2 implementation's process_allgather needed the
    full 2^n array on every host, which cannot serve the 34q scale the
    checkpoint exists for)."""
    amps = qureg.amps
    os.makedirs(directory, exist_ok=True)
    meta_path = os.path.join(directory, _META_NAME)
    if os.path.exists(meta_path) and jax.process_index() == 0:
        os.unlink(meta_path)  # a crash mid-overwrite must not look loadable
    if jax.process_count() > 1:
        # no process may overwrite a shard named by the OLD metadata until
        # the invalidation above is durable, or a crash mid-save would leave
        # stale metadata pointing at a mix of old and new shard files
        from jax.experimental import multihost_utils

        multihost_utils.sync_global_devices("quest_ckpt_invalidate")

    from .resilience import guard as _guard

    local_index = []
    for start, stop, data in _shard_ranges(amps):
        # name shards by their global start offset: unique across processes
        # without coordination (shards partition the amp axis)
        fname = f"amps.shard_{start:016x}.npz"
        host = np.ascontiguousarray(np.asarray(data))
        crc = zlib.crc32(host.tobytes())

        def _write(fname=fname, host=host, start=start, stop=stop) -> str:
            # process-unique tmp name: replicated layouts have several
            # processes writing the same range to the same final name; the
            # atomic replace makes the duplicate writes idempotent, but a
            # shared tmp path would tear mid-write
            tmp = os.path.join(directory,
                               f"{fname}.{jax.process_index()}.tmp")
            with open(tmp, "wb") as f:
                np.savez_compressed(f, amps=host, start=np.int64(start),
                                    stop=np.int64(stop))
            final = os.path.join(directory, fname)
            os.replace(tmp, final)
            return final

        _guard.checkpoint_write(_write)
        local_index.append({"file": fname, "start": int(start),
                            "stop": int(stop), "crc32": int(crc)})

    if jax.process_count() > 1:
        # all shards must be durable before the metadata names them; the
        # index is global, so exchange every process's local index
        from jax.experimental import multihost_utils

        payload = json.dumps(local_index).encode()
        if len(payload) > (1 << 16):  # pragma: no cover - ~600 shards/host
            raise QuESTError(
                f"checkpoint shard index too large ({len(payload)} bytes)")
        gathered = multihost_utils.process_allgather(
            np.frombuffer(payload.ljust(1 << 16), dtype=np.uint8))
        seen = {}
        for row in np.asarray(gathered).reshape(jax.process_count(), -1):
            for e in json.loads(bytes(row).rstrip(b"\x00").decode()):
                # replicated layouts: several processes hold (and wrote) the
                # same range under the same name -- keep one index entry
                seen.setdefault(e["start"], e)
        index = sorted(seen.values(), key=lambda e: e["start"])
        if jax.process_index() != 0:
            return
    else:
        index = local_index

    meta = {
        "format": 2,
        "num_qubits_represented": qureg.num_qubits_represented,
        "is_density_matrix": qureg.is_density_matrix,
        "dtype": str(np.dtype(qureg.dtype)),
        "num_amps_total": qureg.num_amps_total,
        "shards": index,
        "seeds": list(qureg.env.seeds) if qureg.env is not None else [],
        "rng_state": _rng_state_json(qureg.env),
    }
    tmp = os.path.join(directory, _META_NAME + ".tmp")
    with open(tmp, "w") as f:
        json.dump(meta, f)
    os.replace(tmp, os.path.join(directory, _META_NAME))


def _load_range(directory, index, start, stop, dtype, num_amps):
    """Assemble host amplitudes [start, stop) from the shard files covering
    that range (reads only overlapping files)."""
    out = np.empty((2, stop - start), dtype=dtype)
    filled = 0
    for entry in index:
        s, e = entry["start"], entry["stop"]
        if e <= start or s >= stop:
            continue
        try:
            with np.load(os.path.join(directory, entry["file"])) as z:
                data = z["amps"]
        except Exception as exc:
            raise QuESTError(
                f"unreadable checkpoint shard {entry['file']!r}: {exc}"
            ) from exc
        if data.shape != (2, e - s):
            raise QuESTError(
                f"checkpoint shard {entry['file']!r} shape {data.shape} != "
                f"index range {(2, e - s)}")
        if "crc32" in entry:
            crc = zlib.crc32(np.ascontiguousarray(data).tobytes())
            if crc != int(entry["crc32"]):
                from .resilience.errors import QuESTChecksumError
                raise QuESTChecksumError(
                    f"checkpoint shard {entry['file']!r} failed CRC32 "
                    f"verification (payload {crc:#010x} != index "
                    f"{int(entry['crc32']):#010x})",
                    shard=entry["file"],
                    expected_crc=int(entry["crc32"]), actual_crc=int(crc))
        lo, hi = max(s, start), min(e, stop)
        out[:, lo - start:hi - start] = data[:, lo - s:hi - s]
        filled += hi - lo
    if filled != stop - start:
        raise QuESTError(
            f"checkpoint shards cover {filled} of {stop - start} amplitudes "
            f"in [{start}, {stop})")
    return out


def _read_meta(directory: str) -> dict:
    meta_path = os.path.join(directory, _META_NAME)
    if not os.path.exists(meta_path):
        raise QuESTError(f"no checkpoint at {directory!r}")
    try:
        with open(meta_path) as f:
            meta = json.load(f)
    except (OSError, ValueError) as e:
        raise QuESTError(f"unreadable checkpoint metadata: {e}") from e
    if meta.get("format") not in (1, 2):
        raise QuESTError(f"unsupported checkpoint format {meta.get('format')!r}")
    return meta


def verify_snapshot(directory: str) -> dict:
    """Integrity-check a snapshot WITHOUT creating a register: metadata
    parses, every format-2 shard is readable, shape-consistent, CRC32-clean
    and the shards cover [0, num_amps) exactly. Returns the metadata dict;
    raises QuESTError naming the offending shard otherwise. This is what
    segmented resume uses to pick the last *verified* generation."""
    meta = _read_meta(directory)
    num_amps = meta["num_amps_total"]
    if meta["format"] == 1:
        try:
            with np.load(os.path.join(directory, _AMPS_NAME)) as z:
                host = z["amps"]
        except Exception as e:
            raise QuESTError(f"unreadable checkpoint payload: {e}") from e
        if host.shape != (2, num_amps):
            raise QuESTError(
                f"checkpoint amplitude shape {host.shape} != "
                f"{(2, num_amps)}")
    else:
        _load_range(directory, meta["shards"], 0, num_amps, meta["dtype"],
                    num_amps)
    return meta


def loadQureg(directory: str, env: QuESTEnv) -> Qureg:
    """Recreate a register from :func:`saveQureg` output, sharded per
    ``env`` (the snapshot's own sharding is irrelevant). Each process reads
    only the shard files overlapping its own devices' target slices.
    Restores ``env``'s RNG stream so measurement sequences resume
    deterministically. Format-1 (monolithic) snapshots remain loadable.

    Fail-closed ordering: every shard is read, shape-checked and
    CRC32-verified (format 2) BEFORE the register is created or the env
    RNG restored -- a rejected snapshot changes nothing."""
    meta = _read_meta(directory)

    num_amps = meta["num_amps_total"]
    dtype = meta["dtype"]
    n = meta["num_qubits_represented"]
    sharding = env.sharding(num_amps)

    if meta["format"] == 1:
        try:
            with np.load(os.path.join(directory, _AMPS_NAME)) as z:
                host = z["amps"]
        except Exception as e:
            raise QuESTError(f"unreadable checkpoint payload: {e}") from e
        if host.shape != (2, num_amps):
            raise QuESTError(
                f"checkpoint amplitude shape {host.shape} != "
                f"{(2, num_amps)}")
        arr = jax.device_put(host.astype(dtype), sharding)
    else:
        index = meta["shards"]
        if sharding is None:
            host = _load_range(directory, index, 0, num_amps, dtype, num_amps)
            arr = jax.device_put(host, jax.devices()[0]
                                 if env.mesh is None else sharding)
        else:
            # per-device assembly: read only the files this process needs
            pieces = []
            devices = []
            for d, idx in sharding.addressable_devices_indices_map(
                    (2, num_amps)).items():
                sl = idx[1]
                start = sl.start or 0
                stop = sl.stop if sl.stop is not None else num_amps
                host = _load_range(directory, index, start, stop, dtype,
                                   num_amps)
                pieces.append(jax.device_put(host, d))
                devices.append(d)
            arr = jax.make_array_from_single_device_arrays(
                (2, num_amps), sharding, pieces)

    # every payload verified -- only now create and fill the register
    make = createDensityQureg if meta["is_density_matrix"] else createQureg
    qureg = make(n, env)
    qureg.put(arr)

    # only restore the seed/RNG pair when the snapshot actually carries one
    # (a register saved with env=None must not clobber the live env's seeds
    # while leaving its RNG stream untouched)
    if meta.get("rng_state") is not None:
        env.seeds = list(meta.get("seeds", []))
        _restore_rng(env, meta["rng_state"])
    return qureg


def writeStateToCSV(qureg: Qureg, filename: str | None = None) -> str:
    """The reference's reportState format (QuEST_common.c:219-231): a
    ``state_rank_0.csv`` with header and one "re, im" row per amplitude."""
    filename = filename or "state_rank_0.csv"
    host = np.asarray(qureg.amps)
    with open(filename, "w") as f:
        f.write("real, imag\n")
        for k in range(host.shape[1]):
            f.write(f"{host[0, k]}, {host[1, k]}\n")
    return filename


def saveSeeds(env: QuESTEnv, path: str) -> None:
    with open(path, "w") as f:
        json.dump({"seeds": list(env.seeds), "rng_state": _rng_state_json(env)}, f)


def loadSeeds(env: QuESTEnv, path: str) -> None:
    with open(path) as f:
        data = json.load(f)
    env.seeds = list(data.get("seeds", []))
    _restore_rng(env, data.get("rng_state"))


def _rng_state_json(env: QuESTEnv | None):
    if env is None or env.rng is None:
        return None
    name, keys, pos, has_gauss, cached = env.rng.get_state()
    return {"name": name, "keys": np.asarray(keys).tolist(), "pos": int(pos),
            "has_gauss": int(has_gauss), "cached": float(cached)}


def _restore_rng(env: QuESTEnv, state) -> None:
    if state is None or env.rng is None:
        return
    env.rng.set_state((state["name"],
                       np.asarray(state["keys"], dtype=np.uint32),
                       int(state["pos"]), int(state["has_gauss"]),
                       float(state["cached"])))
