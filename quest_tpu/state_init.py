"""State initialisation API (reference QuEST.h:1619-1876, QuEST.c init family).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from . import validation as V
from .ops import init as I
from .registers import Qureg

__all__ = [
    "initBlankState", "initZeroState", "initPlusState", "initClassicalState",
    "initPureState", "initDebugState", "initStateFromAmps", "setAmps",
    "setDensityAmps", "cloneQureg", "setWeightedQureg", "getNumQubits",
    "getNumAmps",
]


def _put_shaped(qureg: Qureg, amps) -> None:
    # env is None when replaying on a Circuit tape (inside jit): there the
    # outer program's sharding propagates via GSPMD and device_put is illegal.
    sharding = qureg.env.sharding(qureg.num_amps_total) if qureg.env is not None else None
    if sharding is not None:
        amps = jax.device_put(amps, sharding)
    qureg.put(amps)


def initBlankState(qureg: Qureg) -> None:
    """All-zero amplitudes (unnormalised) (QuEST.h:1619)."""
    _put_shaped(qureg, I.init_blank(qureg.num_amps_total, qureg.dtype))
    if qureg.qasm_log:
        qureg.qasm_log.record_comment(
            "Here, the register was initialised to an unphysical all-zero-amplitudes 'state'.")


def initZeroState(qureg: Qureg) -> None:
    """Set the register to |0...0> (QuEST.h:194)."""
    if qureg.is_density_matrix:
        amps = I.density_init_classical(qureg.num_amps_total, qureg.dtype, 0)
    else:
        amps = I.init_classical(qureg.num_amps_total, qureg.dtype, 0)
    _put_shaped(qureg, amps)
    if qureg.qasm_log: qureg.qasm_log.record_init_zero()


def initPlusState(qureg: Qureg) -> None:
    """Set the register to |+>^n, every amplitude equal (QuEST.h:195)."""
    if qureg.is_density_matrix:
        amps = I.density_init_plus(qureg.num_amps_total, qureg.dtype)
    else:
        amps = I.init_plus(qureg.num_amps_total, qureg.dtype)
    _put_shaped(qureg, amps)
    if qureg.qasm_log: qureg.qasm_log.record_init_plus()


def initClassicalState(qureg: Qureg, state_index: int) -> None:
    """Set the register to computational basis state |stateInd> (QuEST.h:196)."""
    func = "initClassicalState"
    V.validate_state_index(qureg, state_index, func)
    if qureg.is_density_matrix:
        amps = I.density_init_classical(qureg.num_amps_total, qureg.dtype, state_index)
    else:
        amps = I.init_classical(qureg.num_amps_total, qureg.dtype, state_index)
    _put_shaped(qureg, amps)
    if qureg.qasm_log: qureg.qasm_log.record_init_classical(state_index)


def initPureState(qureg: Qureg, pure: Qureg) -> None:
    """Copy a pure state in; density targets get rho = |psi><psi|
    (QuEST.h:1689; densmatr_initPureState)."""
    func = "initPureState"
    V.validate_second_qureg_state_vec(pure, func)
    V.validate_matching_qureg_dims(qureg, pure, func)
    if qureg.is_density_matrix:
        amps = I.density_from_pure(pure.amps, n=qureg.num_qubits_represented)
    else:
        amps = pure.amps + 0
    _put_shaped(qureg, amps)
    if qureg.qasm_log:
        qureg.qasm_log.record_comment(
            "Here, the register was initialised to an undisclosed given pure state.")


def initDebugState(qureg: Qureg) -> None:
    """amp_i = (2i + (2i+1) i)/10: the deterministic test fixture (QuEST.h:1721)."""
    _put_shaped(qureg, I.init_debug(qureg.num_amps_total, qureg.dtype))
    if qureg.qasm_log: qureg.qasm_log.record_comment("initDebugState")


def initStateFromAmps(qureg: Qureg, reals, imags) -> None:
    """Full overwrite from host arrays (QuEST.h:1748)."""
    func = "initStateFromAmps"
    reals = np.asarray(reals).reshape(-1)
    imags = np.asarray(imags).reshape(-1)
    V._assert(reals.size == qureg.num_amps_total and imags.size == qureg.num_amps_total,
              "Invalid number of amplitudes. Must match the register size.", func)
    _put_shaped(qureg, jnp.asarray(np.stack([reals, imags]), dtype=qureg.dtype))
    if qureg.qasm_log:
        qureg.qasm_log.record_comment(
            "Here, the register was initialised to an undisclosed given pure state.")


def setAmps(qureg: Qureg, start_ind: int, reals, imags, num_amps: int) -> None:
    """Overwrite a contiguous slice (QuEST.h:1797)."""
    func = "setAmps"
    V.validate_state_vec(qureg, func)
    V.validate_num_amps(qureg, start_ind, num_amps, func)
    vals = np.stack([np.asarray(reals).reshape(-1)[:num_amps],
                     np.asarray(imags).reshape(-1)[:num_amps]])
    qureg.put(qureg.amps.at[:, start_ind:start_ind + num_amps].set(
        jnp.asarray(vals, dtype=qureg.dtype)))
    if qureg.qasm_log:
        qureg.qasm_log.record_comment(
            "Here, some amplitudes in the statevector were manually edited.")


def setDensityAmps(qureg: Qureg, start_row: int, start_col: int, reals, imags, num_amps: int) -> None:
    """Overwrite density elements column-wise from (start_row, start_col)
    (QuEST.h:1829). Flat order runs down rows then across columns, matching
    the row-bits-low layout."""
    func = "setDensityAmps"
    V.validate_density_matr(qureg, func)
    dim = 1 << qureg.num_qubits_represented
    start = start_col * dim + start_row
    V._assert(0 <= start_row < dim and 0 <= start_col < dim,
              "Invalid amplitude index. Note amplitudes are zero indexed.", func)
    V._assert(num_amps >= 0 and start + num_amps <= qureg.num_amps_total,
              "Invalid number of amplitudes. Must be >=0 and fit within the register.", func)
    vals = np.stack([np.asarray(reals).reshape(-1)[:num_amps],
                     np.asarray(imags).reshape(-1)[:num_amps]])
    qureg.put(qureg.amps.at[:, start:start + num_amps].set(
        jnp.asarray(vals, dtype=qureg.dtype)))
    if qureg.qasm_log:
        qureg.qasm_log.record_comment(
            "Here, some amplitudes in the density matrix were manually edited.")


def cloneQureg(target: Qureg, source: Qureg) -> None:
    """Overwrite target's state with source's (QuEST.h:1876)."""
    func = "cloneQureg"
    V.validate_matching_qureg_types(target, source, func)
    V.validate_matching_qureg_dims(target, source, func)
    target.put(source.amps + 0)


def setWeightedQureg(fac1: complex, qureg1: Qureg, fac2: complex, qureg2: Qureg,
                     fac_out: complex, out: Qureg) -> None:
    """out = fac1 q1 + fac2 q2 + facOut out (QuEST.h:5688)."""
    func = "setWeightedQureg"
    V.validate_matching_qureg_types(qureg1, qureg2, func)
    V.validate_matching_qureg_types(qureg1, out, func)
    V.validate_matching_qureg_dims(qureg1, qureg2, func)
    V.validate_matching_qureg_dims(qureg1, out, func)
    dt = out.dtype

    def planar(f):
        f = complex(f)
        return jnp.asarray([f.real, f.imag], dtype=dt)

    out.put(I.weighted_sum(planar(fac1), qureg1.amps,
                           planar(fac2), qureg2.amps,
                           planar(fac_out), out.amps))


def getNumQubits(qureg: Qureg) -> int:
    """Number of qubits the register represents (QuEST.h:134)."""
    return qureg.num_qubits_represented


def getNumAmps(qureg: Qureg) -> int:
    """Number of statevector amplitudes, 2^numQubits (QuEST.h:135)."""
    V.validate_state_vec(qureg, "getNumAmps")
    return qureg.num_amps_total
