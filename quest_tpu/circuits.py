"""Circuit: a recorded gate tape compiled into ONE fused XLA program.

The reference applies gates eagerly, one kernel launch (and, when distributed,
one MPI exchange) per gate -- its whole cost model is per-gate
(QuEST_cpu_distributed.c:870-905). On TPU the dominant cost of that scheme is
neither FLOPs nor bandwidth but per-dispatch overhead and lost fusion: XLA
fuses runs of elementwise/diagonal gates into single HBM passes and overlaps
collective traffic with compute *within* one compiled program, never across
programs.

``Circuit`` is therefore the TPU-native execution unit: record the same L5
API calls (same names, same argument order as ``QuEST.h``) against a tape,
then replay the tape symbolically through one ``jax.jit``. Validation and
matrix construction happen once at trace time on the host; the device sees a
single fused program. Eager per-gate application (the reference's model)
remains available by simply calling the API functions directly.

Measurement and host-returning calculations are excluded from tapes (they
need host control flow / RNG); use the eager API for those, or
``lax.cond``-based collapse via ``collapseToOutcome`` eagerly between
circuits.
"""

from __future__ import annotations

import inspect

import jax

from . import telemetry
from .registers import Qureg

#: API names that can be recorded on a tape: mutate qureg.amps, need no host
#: round-trip at run time. (measure/collapse and calc* are excluded --
#: their RECORDABLE forms live in sampling.measure, which draws/forces
#: outcomes branch-free on device instead of host-syncing a probability.)
_TAPEABLE_MODULES = ("gates", "operators", "decoherence", "state_init",
                     "trajectories.noise", "sampling.measure")
_EXCLUDED = {
    "measure", "measureWithStats", "collapseToOutcome",
    # these need host data or aren't pure amps->amps
    "createDiagonalOp", "destroyDiagonalOp", "syncDiagonalOp",
    "initDiagonalOp", "setDiagonalOpElems", "initDiagonalOpFromPauliHamil",
    "createDiagonalOpFromPauliHamilFile", "calcExpecDiagonalOp",
    "initStateFromAmps", "setAmps", "setDensityAmps",
}


def _tape_compatible(fn) -> bool:
    """True iff ``fn``'s signature fits the tape contract: the target Qureg
    is the sole Qureg argument and comes first. Functions taking a second
    register (initPureState, cloneQureg, setWeightedQureg, applyPauliSum,
    mixDensityMatrix, ...) would either leak jit tracers into the other
    register or bake its amplitudes into the executable as a stale constant.
    """
    try:
        params = list(inspect.signature(fn).parameters.values())
    except (TypeError, ValueError):
        return False
    if not params:
        return False

    def is_qureg(p):
        return "Qureg" in str(p.annotation) or "qureg" in p.name.lower()

    return is_qureg(params[0]) and not any(is_qureg(p) for p in params[1:])


def _resolve(name):
    import importlib
    for mod_name in _TAPEABLE_MODULES:
        mod = importlib.import_module(f".{mod_name}", __package__)
        fn = getattr(mod, name, None)
        if fn is not None and callable(fn):
            if not _tape_compatible(fn):
                raise AttributeError(
                    f"'{name}' takes a second Qureg (or none first); it must "
                    f"run eagerly, not on a Circuit tape")
            return fn
    raise AttributeError(
        f"'{name}' is not a tapeable quest_tpu API function "
        f"(measurement and calc* functions must run eagerly)")


#: modules whose tape entries route EVERY amps access through the explicit
#: scheduler's coordinate remapping -- safe to run under a deferred layout
_DEFER_SAFE_MODULES = ("quest_tpu.gates", "quest_tpu.decoherence",
                       "quest_tpu.operators")

#: operators-module entries that DO read/write raw full-state amplitude
#: order (a full 2^N diagonal indexed by flat position; a wholesale state
#: overwrite) -- these still force reconciliation
_DEFER_BARRIER_NAMES = {"applyDiagonalOp", "setQuregToPauliHamil"}


def _defer_safe(f) -> bool:
    """True if tape entry ``f`` may run while the scheduler's deferred
    qubit layout is non-identity. Gate, channel and operator entries remap
    their coordinates through the scheduler (phase functions, projectors
    and sub-diagonal ops are pure index algebra -- remapping is
    scheduler.map_diagonal_qubits; matrixN routes through apply_matrix);
    fused dense/diag blocks route through the same gate primitives.
    Everything else (inits, full-state diagonals, Pallas runs and frame
    swaps) assumes the identity layout and forces reconciliation."""
    from . import fusion

    if getattr(f, "__module__", None) in _DEFER_SAFE_MODULES:
        return getattr(f, "__name__", "") not in _DEFER_BARRIER_NAMES
    return f is fusion._apply_dense_block


def _tape_accesses(tape, num_qubits, is_density, dtype):
    """Per-entry logical-qubit access sets for the deferred scheduler's
    Belady eviction (None = barrier), PLUS the aligned per-entry DENSE
    subsets (qubits used in a relocation-forcing role) the round-6
    relocation batcher prefetches from; returns ``(accesses, dense)``.
    Dense membership mirrors the scheduler's own dispatch: non-diagonal
    matrix targets and X-class targets relocate (apply_matrix / apply_x in
    deferred mode) and channel rows AND columns relocate, while controls,
    parity members, diagonal targets and uncontrolled SWAPs (virtual)
    never do. Dense/diag fused blocks expose their qubits directly; raw
    gate entries are spy-captured; density row events gain their
    conj-shadow column coordinates."""
    import numpy as np

    from . import fusion

    def event_dense(ev):
        """The event's relocation-forcing qubits (row coordinates)."""
        if ev.kind == "x":
            return set(ev.targets)
        if ev.kind == "swap":
            # uncontrolled SWAP is a pure layout update (virtual swap)
            return set(ev.targets) if ev.controls else set()
        if ev.kind == "channel":
            return set(ev.targets)
        if ev.kind == "matrix":
            m = np.asarray(ev.matrix)
            if np.any(m - np.diag(np.diag(m)) != 0):
                return set(ev.targets)
            return set()
        return set()  # diag / parity / aux: comm-free under any layout

    out = []
    dense_out = []
    for f, args, kwargs in tape:
        if not _defer_safe(f):
            out.append(None)
            dense_out.append(None)
            continue
        if f is fusion._apply_dense_block:
            qs = set(args[1])
            if is_density:
                qs |= {q + num_qubits for q in qs}
            out.append(frozenset(qs))
            dense_out.append(frozenset(qs))
            continue
        if getattr(f, "__name__", "") == "_apply_gate_diag":
            # DiagBlock tape entries: (diag, qubits)
            qs = set(args[1])
            if is_density:
                qs |= {q + num_qubits for q in qs}
            out.append(frozenset(qs))
            dense_out.append(frozenset())
            continue
        events = fusion.capture(f, args, kwargs, num_qubits, dtype,
                                is_density=is_density, aux=True)
        if events is None:
            out.append(None)
            dense_out.append(None)
            continue
        qs = set()
        ds = set()
        for ev in events:
            s = set(ev.support)
            d = event_dense(ev)
            if is_density and (not ev.extended or ev.kind == "channel"):
                # channel events carry ROW targets (extended only means "no
                # shadow twin"); their column qubits are accessed too
                s |= {q + num_qubits for q in s}
                d |= {q + num_qubits for q in d}
            qs |= s
            ds |= d
        out.append(frozenset(qs))
        dense_out.append(frozenset(ds))
    return out, dense_out


def _amps_mesh(amps):
    """The 1-D amps mesh a (concrete) amplitude array is sharded over, or
    None for single-device / traced arrays."""
    from jax.sharding import NamedSharding, PartitionSpec

    from .environment import AMP_AXIS

    sharding = getattr(amps, "sharding", None)
    if (isinstance(sharding, NamedSharding)
            and sharding.spec == PartitionSpec(None, AMP_AXIS)
            and sharding.mesh.size > 1):
        return sharding.mesh
    return None


def _register_mesh(qureg):
    """The 1-D amps mesh the register is actually sharded over, or None."""
    return _amps_mesh(qureg.amps)


class Circuit:
    """Deferred-execution circuit over ``num_qubits`` qubits.

    Usage::

        c = Circuit(3)
        c.hadamard(0)
        c.controlledNot(0, 1)
        c.run(qureg)           # compiles once, then reuses the executable

    Any L5 gate/operator/decoherence/init function is available as a method
    (without the leading ``qureg`` argument).
    """

    def __init__(self, num_qubits: int, is_density_matrix: bool = False):
        self.num_qubits = int(num_qubits)
        self.is_density_matrix = bool(is_density_matrix)
        self._tape: list = []
        # identity of this tape revision: executable-cache keys carry it, so
        # mutating the tape invalidates them without any per-circuit dict
        # (compiled replays live in the BOUNDED process-global LRU,
        # engine.cache.executables(), with uniform hit/miss/evict telemetry)
        self._cache_token = object()
        self._lifted_cache = None
        self._fp_cache = None

    # -- recording ----------------------------------------------------------

    def __getattr__(self, name):
        if name.startswith("_") or name in _EXCLUDED:
            raise AttributeError(name)
        fn = _resolve(name)

        def record(*args, **kwargs):
            self.append(fn, *args, **kwargs)

        record.__name__ = name
        return record

    def append(self, fn, *args, **kwargs) -> "Circuit":
        """Record ``fn(qureg, *args, **kwargs)`` on the tape."""
        self._tape.append((fn, args, kwargs))
        self._cache_token = object()
        self._lifted_cache = None
        self._fp_cache = None
        return self

    def __len__(self) -> int:
        return len(self._tape)

    # -- execution ----------------------------------------------------------

    def as_fn(self):
        """Pure amps->amps function replaying the tape (jit-compatible).

        Under an active explicit-mesh scheduler the replay runs in DEFERRED
        permutation mode (parallel.scheduler.DistributedScheduler): gate
        relocation swap-backs are elided and the qubit layout reconciles to
        identity only at barrier entries and at replay end. Entries that
        bypass the scheduler's coordinate remapping (state inits, phase
        functions, Pallas runs) are barriers; gate/channel/dense-block
        entries defer."""
        return self._replay_fn(None)

    def _replay_fn(self, lifted, lo: int = 0, hi: int | None = None):
        """The replay body behind :meth:`as_fn` (``lifted=None``) and the
        parameterized executables (``lifted`` an engine.params.LiftedTape):
        with a lifted tape the returned ``fn(amps, values)`` substitutes the
        bound -- typically traced -- scalars into the slotted entries before
        each application, so gate matrices assemble from runtime values
        inside the one compiled program. Each trace of the parameterized
        form counts ``engine_trace_total{kind=param_replay}`` (the retrace
        detector the serving tests assert on).

        ``lo``/``hi`` restrict the replay to ``tape[lo:hi]`` -- the
        segment programs of :mod:`quest_tpu.segments` (round 13). Slices
        are whole replays in miniature: lookahead, deferred-permutation
        scope, and reconciliation all cover exactly the slice, which is
        sound because segment boundaries are frame-identity points.
        Slicing composes with plain replay only (``lifted`` entries are
        indexed against the whole tape)."""
        from .parallel import scheduler as _dist

        if lifted is not None and (lo != 0 or hi is not None):
            raise ValueError("sliced replay requires lifted=None")
        tape = tuple(self._tape[lo:hi])
        entries = tuple(lifted.entries) if lifted is not None else None
        num_qubits, is_density = self.num_qubits, self.is_density_matrix
        nsv = (2 if is_density else 1) * num_qubits

        lookahead_cell = []  # memoized across retraces

        def fn(amps, values=()):
            if entries is None:
                steps = tape
            else:
                from .engine.params import materialize_entry
                telemetry.inc("engine_trace_total", kind="param_replay")
                steps = [materialize_entry(e, values) for e in entries]
            shell = Qureg(num_qubits, is_density, amps, env=None)
            sched = _dist.active()
            # sliced replays label their defer span with the slice origin
            # so a journaled segmented plan re-prices per segment
            # (plancheck.check_schedule "segment" records)
            seg_label = lo if (lo != 0 or hi is not None) else None
            started = sched.begin_defer(segment=seg_label) \
                if sched is not None else False
            try:
                if started:
                    if not lookahead_cell:
                        # access sets come from the ORIGINAL tape: entries
                        # carrying value slots fail capture and barrier,
                        # identically for every values binding
                        lookahead_cell.append(_tape_accesses(
                            tape, num_qubits, is_density, shell.dtype))
                    sched.set_lookahead(*lookahead_cell[0])
                for i, (f, args, kwargs) in enumerate(steps):
                    if sched is not None and sched.deferring:
                        sched.advance(i)
                        if not _defer_safe(f):
                            shell.put(sched.reconcile(shell.amps, nsv))
                    f(shell, *args, **kwargs)
                if started:
                    shell.put(sched.end_defer(shell.amps, nsv))
                    sched.set_lookahead(None)
                return shell.amps
            except BaseException:
                if started:
                    # the amps are being discarded; a stale non-identity
                    # layout must not leak into the next replay
                    sched.abort_defer()
                raise

        return fn

    def compiled(self, donate: bool = True):
        """The tape as one jitted executable, cached per execution mode in
        the process-global bounded LRU (engine.cache.executables(): uniform
        eviction + ``plan_cache_{hit,miss,evict}_total`` telemetry -- the
        per-circuit dict of earlier rounds grew without limit per
        (mode, mesh) key).

        Gate routing (default GSPMD vs the explicit_mesh scheduler) is
        trace-time state, so the cache is keyed on the active scheduler's
        mesh -- entering/leaving ``explicit_mesh`` retraces rather than
        silently replaying the other mode's executable.
        """
        from . import fusion
        from .engine import cache as _ec
        from .parallel import scheduler as _dist
        sched = _dist.active()
        mesh = sched.mesh if sched else None
        pmesh = fusion.active_pallas_mesh()
        key = ("circuit", self._cache_token, donate, mesh, pmesh)

        def build():
            inner = jax.jit(self.as_fn(), donate_argnums=(0,) if donate else ())

            def fn(amps, _inner=inner, _mesh=mesh, _pmesh=pmesh):
                # jit traces on first *call*, which may happen under a
                # different scheduler/pallas-mesh context than the one this
                # executable is keyed on -- pin the modes captured here.
                # With no ambient pallas mesh, derive it from the concrete
                # amps so calling compiled() directly on a sharded register
                # behaves like run() (Pallas/Kraus paths would otherwise
                # trace meshless and GSPMD-gather the shards onto one device)
                pm = _pmesh if _pmesh is not None else _amps_mesh(amps)
                with _dist.explicit_mesh(_mesh), fusion.pallas_mesh(pm):
                    return _inner(amps)

            return fn

        return _ec.executables().get_or_create(key, build)

    # -- parameterized execution (the serving engine's entry points) --------

    def lifted(self):
        """This tape's :class:`~quest_tpu.engine.params.LiftedTape` (value
        slots factored out of Params AND constant angles/Complex scalars),
        memoized per tape revision."""
        from .engine import params as _prm
        tok = self._cache_token
        if self._lifted_cache is None or self._lifted_cache[0] is not tok:
            self._lifted_cache = (tok, _prm.lift_tape(tuple(self._tape)))
        return self._lifted_cache[1]

    @property
    def param_names(self) -> tuple:
        """Ordered unique :class:`~quest_tpu.engine.params.Param` names
        recorded on the tape."""
        return self.lifted().param_names

    def fingerprint(self) -> str:
        """Structure fingerprint of the tape (gate names, targets/controls,
        value-slot kinds -- never the lifted values): the executable-cache
        key under which structure-equal circuits share compiled replays.
        See engine.cache.structure_fingerprint."""
        from .engine import cache as _ec
        tok = self._cache_token
        if self._fp_cache is None or self._fp_cache[0] is not tok:
            self._fp_cache = (tok, _ec.structure_fingerprint(
                self._tape, self.num_qubits, self.is_density_matrix))
        return self._fp_cache[1]

    def parameterized(self, donate: bool = True, reduce=None):
        """The tape as ONE jitted executable whose lifted values (Params and
        constant angles/Complex scalars) are runtime arguments: a
        :class:`~quest_tpu.engine.params.ParamExecutable` called as
        ``exe(amps, {"theta": 0.3})``. Changing values never retraces --
        gate matrices assemble from the traced scalars inside the program
        (matrices.py traced branches), including between the static kernel
        runs of a fused Pallas plan.

        ``reduce`` (round 19): an optional traceable terminal stage
        composed INSIDE the jitted program -- the executable returns
        ``reduce(final_amps)`` (e.g. a shot table, an expectation)
        instead of the amplitudes, so the 2^N state never crosses to the
        host. Must be a stable (cached) callable: it is part of the
        executable-cache key.

        Cached in the global LRU keyed by (structure fingerprint, mode
        meshes): two structure-equal circuits -- same ansatz, different
        recorded angles -- share one compiled executable
        (``plan_cache_hit_total``)."""
        from . import fusion
        from .engine import cache as _ec
        from .engine.params import ParamExecutable
        from .parallel import scheduler as _dist
        sched = _dist.active()
        mesh = sched.mesh if sched else None
        pmesh = fusion.active_pallas_mesh()
        lifted = self.lifted()
        fp = self.fingerprint()
        key = ("param", fp, donate, mesh, pmesh, reduce)

        def build():
            body = self._replay_fn(lifted)
            if reduce is not None and getattr(reduce, "wants_values", False):
                # values-aware reduce (the adjoint gradient sweep): the
                # terminal stage sees the bound slot values too, so the
                # backward walk re-assembles daggered gates from the same
                # traced scalars the forward replay used
                whole = lambda amps, values: reduce(body(amps, values),  # noqa: E731
                                                    values)
            elif reduce is not None:
                whole = lambda amps, values: reduce(body(amps, values))  # noqa: E731
            else:
                whole = body
            inner = jax.jit(whole, donate_argnums=(0,) if donate else ())

            def fn(amps, values, _inner=inner, _mesh=mesh, _pmesh=pmesh):
                pm = _pmesh if _pmesh is not None else _amps_mesh(amps)
                with _dist.explicit_mesh(_mesh), fusion.pallas_mesh(pm):
                    return _inner(amps, values)

            return fn

        return ParamExecutable(_ec.executables().get_or_create(key, build),
                               lifted, fp)

    def gradient(self, hamiltonian, *, donate: bool = True, dtype=None):
        """Compile the tape's adjoint-state gradient against a Pauli-sum
        Hamiltonian (:mod:`quest_tpu.gradients`): one forward sweep, one
        backward walk daggering every gate while harvesting ⟨λ|∂G/∂θ|φ⟩
        per slot -- all lowered into ONE jitted program dispatched as
        ``route=grad_request``. Returns a
        :class:`~quest_tpu.gradients.GradExecutable` called as
        ``grad(amps, {"theta": 0.3}) -> {"value", "grads", "slot_grads"}``.

        Non-invertible tape items (measurement, trajectory noise,
        channels) raise a typed :class:`QuESTError` here, at lift time,
        naming the offending site."""
        from .gradients import gradient_executable
        return gradient_executable(self, hamiltonian, donate=donate,
                                   dtype=dtype)

    def fused(self, max_qubits: int = 5, dtype=None,
              pallas: bool = False, shard_devices: int | None = None,
              ring_depth: int | None = None,
              comm_pipeline: int | None = None,
              comm_pipeline_dcn: int | None = None) -> "Circuit":
        """A new Circuit with runs of gates contracted into ``max_qubits``-
        qubit unitaries at trace time (see :mod:`quest_tpu.fusion`).

        Semantics-preserving for arbitrary tapes: entries that cannot be
        captured as gate primitives (decoherence, phase functions, inits)
        pass through unchanged and act as fusion barriers.

        ``pallas=True`` additionally routes gate runs through the fused
        Pallas kernel (ops.pallas_gates) with two-frame scheduling: one HBM
        pass per run instead of one GEMM pass per dense block. Density
        tapes plan over the flattened 2n-qubit state with explicit
        conj-shadow ops (fusion._shadow_pop). ``shard_devices`` plans for execution on a register
        sharded over that many devices: the tile limit shrinks to the
        shard-local size so every emitted run is per-shard executable under
        shard_map (fusion._shard_map_pallas_run); Circuit.run keeps that
        per-shard path active inside the jitted replay by deriving the
        execution mesh from the register it is given (fusion.pallas_mesh).

        ``ring_depth`` is the PLAN-level knob for the manual-DMA ring
        (ops.pallas_gates._make_dma_kernel): stamped onto every emitted
        PallasRun, it outranks the QUEST_PALLAS_RING env default when the
        runs execute. None leaves the process default in charge.

        ``comm_pipeline`` is the comm-side twin: the collective pipeline
        depth (parallel.exchange) stamped onto every emitted PallasRun and
        FrameSwap, outranking the QUEST_COMM_PIPELINE env default when the
        plan's frame relabelings ride the explicit scheduler's grouped
        collectives. Bit-identical at every depth; 1 = the monolithic
        launch. None leaves the process default in charge.

        ``comm_pipeline_dcn`` (round 15) is the per-link-class refinement:
        sub-collectives that cross a DCN shard bit (num_slices > 1 under
        the explicit scheduler) pipeline at this depth while ICI ones keep
        ``comm_pipeline``. None defers to QUEST_COMM_PIPELINE_DCN, then to
        the base depth (parallel.exchange.resolve_pipeline_dcn).
        """
        import numpy as np

        from . import fusion
        from .precision import real_dtype

        tile_bits = None
        shard_boundary = None
        if pallas:
            from .ops.pallas_gates import LANE_BITS, local_qubits
            # density tapes plan over the flattened 2n-qubit state: the
            # conj-shadow column qubits are explicit ops in the plan
            # (fusion._shadow_pop), so the tile geometry is the state's
            n_eff = (2 if self.is_density_matrix else 1) * self.num_qubits
            if shard_devices and shard_devices > 1:
                d = int(shard_devices)
                if d & (d - 1):
                    raise ValueError(
                        f"shard_devices must be a power of 2 (got {d}); "
                        "amplitude sharding splits whole top qubits")
                n_eff -= d.bit_length() - 1
                # align frame blocks to the shard boundary: frames below
                # it relabel with shard-LOCAL transposes (no collective)
                shard_boundary = n_eff
            # below 2^LANE_BITS amplitudes there is no lane tile to build;
            # the ordinary fusion path handles such registers
            if n_eff > LANE_BITS:
                from .ops.pallas_df import df_wanted
                dt_plan = np.dtype(dtype) if dtype else real_dtype()
                if dt_plan == np.dtype("float64") and df_wanted():
                    # f64 on the df route (TPU always; elsewhere opt-in
                    # via QUEST_PALLAS_DF=1) runs the double-float
                    # kernel, whose tuned tile is smaller
                    # (ops/pallas_df.DF_SUBLANES) -- sharded plans built
                    # here use the SAME geometry per shard, so the
                    # local/dense split matches the df executor; the
                    # native-f64 interpreter geometry applies otherwise
                    from .ops.pallas_df import DF_SUBLANES
                    tile_bits = local_qubits(n_eff, DF_SUBLANES)
                else:
                    tile_bits = local_qubits(n_eff)
        dt = np.dtype(dtype) if dtype else real_dtype()
        if tile_bits is not None and shard_boundary is not None:
            # sharded: try plain and boundary-aligned frame tilings, keep
            # the one with fewer collective transposes
            p = fusion.plan_pallas_sharded(
                tuple(self._tape), self.num_qubits, dt, max_qubits,
                tile_bits, shard_boundary,
                is_density=self.is_density_matrix)
        else:
            p = fusion.plan(tuple(self._tape), self.num_qubits, dt,
                            max_qubits=max_qubits,
                            pallas_tile_bits=tile_bits,
                            is_density=self.is_density_matrix)
        if ring_depth is not None:
            for item in p.items:
                if isinstance(item, fusion.PallasRun):
                    item.ring_depth = int(ring_depth)
        if comm_pipeline is not None:
            for item in p.items:
                if isinstance(item, (fusion.PallasRun, fusion.FrameSwap)):
                    item.comm_pipeline = int(comm_pipeline)
        if comm_pipeline_dcn is not None:
            for item in p.items:
                if isinstance(item, (fusion.PallasRun, fusion.FrameSwap)):
                    item.comm_pipeline_dcn = int(comm_pipeline_dcn)
        # round 13: stamp each frame-carrying item with its frame-identity
        # segment index (the single-dispatch segment programs' seams;
        # plancheck QT107 re-derives and cross-checks the stamps)
        from . import segments as _segments
        _segments.stamp_plan(
            p, (2 if self.is_density_matrix else 1) * self.num_qubits)
        from . import analysis
        if analysis.verify_enabled():
            # QUEST_VERIFY=1: statically verify the plan's frame/ring
            # invariants at compile time; raises AnalysisError on
            # error-severity findings (docs/analysis.md). Sharded plans
            # are verified over the FULL state-vector space: frame grid
            # blocks may reach sharded qubits (collective transposes).
            plan_space = \
                (2 if self.is_density_matrix else 1) * self.num_qubits
            analysis.verify_plan(
                p, nsv=plan_space, dtype=dt, shard_qubits=shard_boundary,
                location=f"fused({self.num_qubits}q)")
        out = Circuit(self.num_qubits, self.is_density_matrix)
        out._tape = fusion.as_tape(p)
        return out

    def blocks(self, max_gates: int) -> list:
        """Split the tape into sub-circuits of at most ``max_gates`` gates.

        One arbitrarily deep circuit as a single XLA program eventually
        exhausts the compiler (the graph grows with tape length x state
        size); chaining a few block-sized executables with donated buffers
        keeps per-program compilation bounded while retaining fusion within
        each block. Runtime cost is one extra dispatch per block.
        """
        if max_gates < 1:
            raise ValueError("max_gates must be >= 1")
        parts = []
        for i in range(0, len(self._tape), max_gates):
            part = Circuit(self.num_qubits, self.is_density_matrix)
            part._tape = list(self._tape[i:i + max_gates])
            parts.append(part)
        return parts

    def compiled_blocks(self, max_gates: int, donate: bool = True):
        """Like :meth:`compiled`, but as a chain of block-sized executables.
        Cached like :meth:`compiled` (the same bounded global LRU) so
        repeated calls reuse the underlying executables instead of
        retracing every block."""
        from . import fusion
        from .engine import cache as _ec
        from .parallel import scheduler as _dist
        sched = _dist.active()
        key = ("circuit_blocks", self._cache_token, max_gates, donate,
               sched.mesh if sched else None, fusion.active_pallas_mesh())

        def build():
            fns = [b.compiled(donate=donate) for b in self.blocks(max_gates)]

            def chained(amps, _fns=tuple(fns)):
                for f in _fns:
                    telemetry.inc("device_dispatch_total", route="block")
                    amps = f(amps)
                return amps

            return chained

        return _ec.executables().get_or_create(key, build)

    def compiled_segments(self, max_items: int | None = None,
                          donate: bool = True):
        """The tape as a chain of frame-identity-aligned segment programs
        (round 13, :mod:`quest_tpu.segments`): each segment is ONE jitted
        dispatch covering up to ``max_items`` tape entries, cut only at
        frame-identity seams. Supersedes :meth:`compiled_blocks` for deep
        tapes -- same bounded per-program compile size, but the seams are
        legal checkpoint/resume points and the dispatch tax is the
        SEGMENT count, not the block count (``max_items=None`` = the
        whole tape as one program). The chain exposes its link count as
        ``.num_segments``; every link launch counts
        ``device_dispatch_total{route="segment"}``."""
        from . import segments
        return segments.chain_executable(self, max_items=max_items,
                                         donate=donate)

    def compiled_request(self, donate: bool = True, reduce=None):
        """The WHOLE request -- every frame-identity segment plus an
        optional final traceable ``reduce(amps)`` -- composed into ONE
        dispatched program with the state buffer donated end-to-end
        (round 18, :func:`quest_tpu.segments.request_executable`).
        ``dispatches_per_circuit`` hits its floor of 1: calling the
        returned executable counts exactly one
        ``device_dispatch_total{route="request"}`` however many segments
        (``.num_segments``) were composed."""
        from . import segments
        return segments.request_executable(self, donate=donate,
                                           reduce=reduce)

    def run(self, qureg: Qureg) -> Qureg:
        """Apply the circuit to ``qureg`` (mutates its amps, like the C API).

        The whole tape is one jitted program -- already the degenerate
        single-dispatch segment -- counted as
        ``device_dispatch_total{route="circuit"}`` (host-side: counters
        inside the program would count traces, not launches)."""
        if qureg.num_qubits_represented != self.num_qubits or \
           qureg.is_density_matrix != self.is_density_matrix:
            raise ValueError(
                f"Circuit({self.num_qubits}q, density={self.is_density_matrix}) "
                f"cannot run on {qureg!r}")
        from . import fusion
        with fusion.pallas_mesh(_register_mesh(qureg)):
            telemetry.inc("device_dispatch_total", route="circuit")
            qureg.put(self.compiled()(qureg.amps))
        return qureg

    def run_segmented(self, target, *, checkpoint_dir: str,
                      every_n_items: int = 1, keep: int = 2) -> Qureg:
        """Run the tape in segments, checkpointing at frame-identity
        boundaries so a preempted run resumes bit-identically from the
        last *verified* snapshot (:func:`quest_tpu.resilience.segmented.
        resume_segmented`). ``target`` is a Qureg or a QuESTEnv (a fresh
        zero-state register is created). ``every_n_items`` spaces the
        checkpoint cadence in tape items; ``keep`` bounds snapshot
        generations retained on disk. See docs/resilience.md."""
        from .resilience import segmented as _seg
        return _seg.run_segmented(self, target, checkpoint_dir=checkpoint_dir,
                                  every_n_items=every_n_items, keep=keep)
