"""Parameter-shift gradients: the independent second oracle.

Not a serving path -- 2P (or 4P) full replays per gradient where the
adjoint sweep does ~3 -- but an *analytically exact* cross-check that
shares nothing with the adjoint code beyond the forward replay: rotation
generators with eigenvalues ±1 and the phase family (unit eigenvalue gap)
obey the two-term rule

    dE/dθ = [E(θ+π/2) - E(θ-π/2)] / 2,

while controlled rotations (generator eigenvalues {-1, 0, +1}, so E mixes
frequencies θ/2 and θ) need the four-term rule

    dE/dθ = c₊[E(θ+π/2) - E(θ-π/2)] - c₋[E(θ+3π/2) - E(θ-3π/2)],
    c± = (√2 ± 1) / (4√2).

Complex (compact-unitary) slots have no shift rule -- ``jax.grad`` covers
those in the test matrix; asking for them here raises.
"""

from __future__ import annotations

import numpy as np

from ..engine.params import _SlotRef, bind as bind_values
from ..validation import QuESTError
from .adjoint import _FIELDS, _entry_view
from .expectation import hamiltonian_terms

__all__ = ["parameter_shift"]

#: four-term rule coefficients for {-1, 0, +1} generator spectra
_C_PLUS = (np.sqrt(2.0) + 1.0) / (4.0 * np.sqrt(2.0))
_C_MINUS = (np.sqrt(2.0) - 1.0) / (4.0 * np.sqrt(2.0))

#: families whose E(θ) is a pure frequency-1 trig polynomial
_TWO_TERM = {
    "rotateX", "rotateY", "rotateZ", "rotateAroundAxis", "multiRotateZ",
    "multiRotatePauli", "phaseShift", "controlledPhaseShift",
    "multiControlledPhaseShift",
}
#: families mixing frequencies θ/2 and θ (controlled ±1 generators)
_FOUR_TERM = {
    "controlledRotateX", "controlledRotateY", "controlledRotateZ",
    "controlledRotateAroundAxis", "multiControlledMultiRotateZ",
    "multiControlledMultiRotatePauli",
}


def _slot_families(lifted):
    """slot index -> owning gate family name."""
    fam = {}
    for fn, args, kwargs in lifted.entries:
        name = getattr(fn, "__name__", str(fn))
        if name not in _FIELDS:
            continue
        for v in _entry_view(name, args, kwargs).values():
            if isinstance(v, _SlotRef):
                fam[v.index] = name
    return fam


def parameter_shift(circuit, hamiltonian, amps, params=None):
    """Full gradient of ⟨H⟩ by parameter shifts -- ``{"value", "grads",
    "slot_grads"}`` matching :func:`adjoint.grad_reduce`'s layout. Every
    shifted evaluation replays the SAME cached expectation executable with
    a perturbed values tuple (no retraces), but there are 2-4 of them per
    slot: use this as an oracle, not a serving route."""
    from ..sampling.request import expectation_reduce

    codes, coeffs = hamiltonian_terms(hamiltonian, circuit.num_qubits)
    red = expectation_reduce(n=circuit.num_qubits, codes=codes,
                             coeffs=coeffs, density=circuit.is_density_matrix)
    ex = circuit.parameterized(donate=False, reduce=red)
    lifted = ex.lifted
    values = list(bind_values(lifted, params))
    fam = _slot_families(lifted)

    def energy(vals):
        return float(ex.with_values(amps, tuple(vals)))

    def shifted(idx, delta):
        vals = list(values)
        vals[idx] = np.asarray(float(vals[idx]) + delta,
                               dtype=np.asarray(vals[idx]).dtype)
        return energy(vals)

    slot_grads = []
    for s in lifted.slots:
        name = fam.get(s.index)
        if s.kind != "real" or name is None:
            raise QuESTError(
                f"parameter_shift: slot {s.index} ({s.kind}, "
                f"{name or 'unknown family'}) has no shift rule -- use "
                "jax.grad or the adjoint engine", "parameter_shift")
        if name in _TWO_TERM:
            g = (shifted(s.index, np.pi / 2)
                 - shifted(s.index, -np.pi / 2)) / 2.0
        elif name in _FOUR_TERM:
            g = (_C_PLUS * (shifted(s.index, np.pi / 2)
                            - shifted(s.index, -np.pi / 2))
                 - _C_MINUS * (shifted(s.index, 3 * np.pi / 2)
                               - shifted(s.index, -3 * np.pi / 2)))
        else:  # pragma: no cover - _FIELDS is partitioned above
            raise QuESTError(
                f"parameter_shift: no rule for family '{name}'",
                "parameter_shift")
        slot_grads.append(g)

    named = {}
    for s, g in zip(lifted.slots, slot_grads):
        if s.name is not None:
            named[s.name] = named.get(s.name, 0.0) + g
    return {"value": energy(values), "grads": named,
            "slot_grads": tuple(slot_grads)}
