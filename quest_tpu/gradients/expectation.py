"""Pauli-sum Hamiltonians for the adjoint gradient engine.

The adjoint sweep (quest_tpu/gradients/adjoint.py) needs the Hamiltonian in
two forms:

- a *static* ``(codes, coeffs)`` description that can key executable caches
  (same normalisation as :func:`quest_tpu.calculations.calcExpecPauliSum`:
  codes are per-qubit Pauli ids 0..3, coeffs are real), and
- a traceable *application* λ = H|ψ⟩ building the costate the backward walk
  drags through the daggered tape.

Application goes through the low-level gate helpers on a shell register, so
under an active explicit scheduler each Pauli factor rides the same
relocation machinery as the forward gates (a sharded λ build is just more
plan), while the unsharded path reduces to the plain kernel calls
``calculations._pauli_prod_amps`` uses.
"""

from __future__ import annotations

import numpy as np

from .. import gates as G
from .. import matrices as M
from ..ops import reduce as R
from ..registers import Qureg
from ..validation import QuESTError

__all__ = ["hamiltonian_terms", "apply_hamiltonian", "expectation_value"]


def hamiltonian_terms(hamiltonian, num_qubits: int):
    """Normalise a Hamiltonian spec to static ``(codes, coeffs)`` tuples.

    Accepts a :class:`quest_tpu.PauliHamil` or a ``(pauli_codes,
    term_coeffs)`` pair in ``calcExpecPauliSum`` layout (codes flat or
    ``(terms, qubits)``-shaped, ids 0..3). Rows narrower than the register
    pad with identities on the high qubits. The result is hashable -- it
    keys the cached gradient reduce alongside the circuit fingerprint.
    """
    from ..datatypes import PauliHamil

    if isinstance(hamiltonian, PauliHamil):
        codes, coeffs = hamiltonian.pauli_codes, hamiltonian.term_coeffs
    else:
        try:
            codes, coeffs = hamiltonian
        except (TypeError, ValueError):
            raise QuESTError(
                "hamiltonian must be a PauliHamil or a (pauli_codes, "
                "term_coeffs) pair", "gradient") from None
    coeffs = np.asarray(coeffs, dtype=np.float64).reshape(-1)
    if coeffs.size == 0:
        raise QuESTError("hamiltonian has no terms", "gradient")
    if not np.all(np.isfinite(coeffs)):
        raise QuESTError("hamiltonian coefficients must be finite reals",
                         "gradient")
    codes = np.asarray(codes, dtype=np.int32).reshape(coeffs.size, -1)
    if codes.shape[1] > num_qubits:
        raise QuESTError(
            f"hamiltonian acts on {codes.shape[1]} qubits but the register "
            f"has {num_qubits}", "gradient")
    if codes.shape[1] < num_qubits:
        pad = np.zeros((coeffs.size, num_qubits - codes.shape[1]), np.int32)
        codes = np.concatenate([codes, pad], axis=1)
    if codes.min() < 0 or codes.max() > 3:
        raise QuESTError("Pauli codes must be in 0..3", "gradient")
    return (tuple(tuple(int(c) for c in row) for row in codes),
            tuple(float(c) for c in coeffs))


def _apply_pauli_term(shell: Qureg, term) -> None:
    """Apply one Pauli string (per-qubit ids) through the gate helpers."""
    for t, p in enumerate(term):
        if p == 1:
            G._apply_gate_x(shell, (t,))
        elif p == 2:
            G._apply_gate_matrix(shell, M.PAULI_Y_M, (t,))
        elif p == 3:
            G._apply_gate_diag(shell, [1.0, -1.0], (t,))


def apply_hamiltonian(amps, *, codes, coeffs, num_qubits: int):
    """λ = H|ψ⟩ for a Pauli-sum H, traceable, scheduler-aware.

    One term's worth of extra state at a time: the accumulator plus a shell
    register per term -- the O(1)-state property the adjoint method exists
    for (vs parameter-shift's 2P full replays).
    """
    acc = None
    for term, c in zip(codes, coeffs):
        if any(term):
            shell = Qureg(num_qubits, False, amps, env=None)
            _apply_pauli_term(shell, term)
            contrib = shell.amps
        else:
            contrib = amps
        acc = contrib * c if acc is None else acc + contrib * c
    return acc


def expectation_value(amps, lam, chunks: int = 64):
    """Re⟨ψ|λ⟩ -- the forward value E = ⟨ψ|H|ψ⟩ when ``lam`` is
    :func:`apply_hamiltonian`'s costate.

    Reduction order is FIXED independently of sharding: per-chunk partial
    sums (chunk boundaries align with any power-of-two shard layout, so
    each partial is a single-device contiguous reduce) folded sequentially
    by a scan. The same value bits come out of the unsharded and the
    8-device explicit-scheduler route -- the serving contract the gradient
    tests pin down.
    """
    import jax
    import jax.numpy as jnp

    prod = amps[0] * lam[0] + amps[1] * lam[1]
    m = prod.shape[-1]
    k = min(chunks, m)
    part = prod.reshape(k, m // k).sum(axis=1)

    def body(c, x):
        return c + x, None

    total, _ = jax.lax.scan(body, jnp.zeros((), prod.dtype), part)
    return total
