"""Adjoint-state reverse-mode differentiation of parameterized tapes.

The method (Jones & Gacon, arXiv:2009.02823): for E(θ) = ⟨ψ(θ)|H|ψ(θ)⟩
with |ψ⟩ = U_P···U_1|ψ₀⟩, run ONE forward sweep to |ψ⟩, build the costate
λ = H|ψ⟩, then walk backward k = P..1 keeping two registers in lockstep --
φ ← U_k†φ and λ ← U_k†λ -- harvesting each parameter's derivative from the
bracket dE/dθ_k = 2·Re⟨λ_k|∂U_k|φ_{k-1}⟩ along the way. Total cost is
~3 sweeps and O(1) extra state, vs parameter-shift's 2P full replays.

The whole thing is a *reduce* over the forward replay: ``grad_reduce``
returns a finalize callable (``wants_values=True``) that
``Circuit.parameterized`` / the engine batcher compose as
``reduce(body(amps, values), values)``, so forward + backward + all P
accumulations lower into ONE jitted program -- one device dispatch per
gradient (``route=grad_request``), vmappable over T parameter sets.

Derivative rules per lifted family (``engine/params._LIFTABLE``):

- rotations (rotate{X,Y,Z}, rotateAroundAxis, multiRotateZ/Pauli and their
  controlled forms), generator G with U = exp(-iθG/2) on the controlled
  block: ∂U = -(i/2)(Π₁⊗G)·U, so dE/dθ = Im⟨λ|(Π₁⊗G)|φ_k⟩ evaluated on
  the POST-gate state (the (Π₁⊗G)(Π₀⊗I) cross term vanishes);
- phase shifts: U = diag(1,…,e^{iθ}) gives ∂U = iΠ·U and
  dE/dθ = -2·Im⟨λ|Π|φ_k⟩ with Π the all-ones projector over every
  involved qubit;
- compactUnitary(α, β) (non-holomorphic, two complex slots): per real
  component on the PRE-gate state φ' -- ∂U/∂xα = I, ∂U/∂yα = iZ,
  ∂U/∂xβ = -iY, ∂U/∂yβ = iX -- packed to complex cotangents in
  ``jax.grad``'s convention (∂E/∂x + i·∂E/∂y for C→R).

Chain rule through the slot graph: contributions accumulate per *slot*
(so a constant-folded anonymous slot gets its own derivative) and named
slots sharing one Param sum into that Param's gradient.

Inverses ride the ordinary routes: parameterized families dagger through
their own public gate functions (negated angle / (α,β) → (α*, -β), traced
branches included), concrete entries dagger through the fusion planner's
spy capture (matrix → M†, diag → conj, parity → -θ, x/swap self-inverse),
so a sharded backward sweep re-uses the explicit scheduler's relocation
machinery gate by gate -- the reversed forward plan. Anything
non-invertible (measurement, trajectory Kraus, channels, pallas-run plan
entries) raises a typed QuESTError at lift time naming the site.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional

import jax.numpy as jnp
import numpy as np

from .. import gates as G
from .. import matrices as M
from .. import telemetry
from ..engine.params import _SlotRef
from ..ops import reduce as R
from ..registers import Qureg
from ..validation import QuESTError
from .expectation import apply_hamiltonian, expectation_value, hamiltonian_terms

__all__ = ["grad_reduce", "gradient_executable", "plan_backward",
           "check_differentiable", "GradExecutable"]


#: positional field names (qureg excluded) per differentiable family --
#: the merge key turning a tape entry's (args, kwargs) into one view
_FIELDS = {
    "phaseShift": ("target", "angle"),
    "controlledPhaseShift": ("q1", "q2", "angle"),
    "multiControlledPhaseShift": ("qubits", "angle"),
    "rotateX": ("target", "angle"),
    "rotateY": ("target", "angle"),
    "rotateZ": ("target", "angle"),
    "rotateAroundAxis": ("target", "angle", "axis"),
    "controlledRotateX": ("control", "target", "angle"),
    "controlledRotateY": ("control", "target", "angle"),
    "controlledRotateZ": ("control", "target", "angle"),
    "controlledRotateAroundAxis": ("control", "target", "angle", "axis"),
    "multiRotateZ": ("qubits", "angle"),
    "multiControlledMultiRotateZ": ("controls", "targets", "angle"),
    "multiRotatePauli": ("targets", "paulis", "angle"),
    "multiControlledMultiRotatePauli": ("controls", "targets", "paulis",
                                        "angle"),
    "compactUnitary": ("target", "alpha", "beta"),
    "controlledCompactUnitary": ("control", "target", "alpha", "beta"),
}

#: jax.grad packs a C→R cotangent as ∂E/∂x - i·∂E/∂y (2·∂E/∂z in
#: Wirtinger terms); complex slot gradients follow the same convention so
#: the oracle comparison is sign-exact
_CPLX_IM = -1.0


def _entry_view(name, args, kwargs) -> dict:
    """Field -> value (``_SlotRef`` template marker or structure constant)."""
    fields = _FIELDS[name]
    view = dict(zip(fields, args))
    for k, v in (kwargs or {}).items():
        view[k] = v
    missing = [f for f in fields if f not in view]
    if missing:
        raise QuESTError(
            f"tape entry '{name}' is missing arguments {missing}", "gradient")
    return view


def _slot_refs(args, kwargs):
    return [a for a in list(args) + list((kwargs or {}).values())
            if isinstance(a, _SlotRef)]


# ---------------------------------------------------------------------------
# derivative rules: static "bracket step" programs per family
# ---------------------------------------------------------------------------

def _proj(qubits):
    """|1⟩⟨1| per qubit -- the controlled-block projector Π₁."""
    return tuple(("diag", (0.0, 1.0), (int(q),)) for q in qubits)


def _zs(qubits):
    return tuple(("diag", (1.0, -1.0), (int(q),)) for q in qubits)


def _pauli_steps(targets, paulis):
    steps = []
    for t, p in zip(targets, paulis):
        p = int(p)
        if p == 1:
            steps.append(("x", None, (int(t),)))
        elif p == 2:
            steps.append(("matrix", M.PAULI_Y_M, (int(t),)))
        elif p == 3:
            steps.append(("diag", (1.0, -1.0), (int(t),)))
    return tuple(steps)


def _axis_generator(axis) -> np.ndarray:
    """Normalised (x·X + y·Y + z·Z) -- rotateAroundAxis's generator."""
    x, y, z = float(axis.x), float(axis.y), float(axis.z)
    norm = np.sqrt(x * x + y * y + z * z)
    if norm == 0.0:
        raise QuESTError("rotateAroundAxis axis has zero norm", "gradient")
    return np.array([[z, x - 1j * y], [x + 1j * y, -z]],
                    dtype=np.complex128) / norm


def _rules(name, view):
    """``(post, pre)`` contribution lists for one entry.

    Each contribution is ``(field, coef, part, steps, comp)``: the slot at
    ``view[field]`` accumulates ``coef * part⟨λ|Op|φ⟩`` where ``Op`` is the
    ``steps`` program, ``part`` picks Re/Im of the bracket, and ``comp``
    says which component of a complex slot it feeds (None for real slots).
    ``post`` brackets evaluate on the post-gate φ_k, ``pre`` on φ_{k-1}.
    """
    post, pre = [], []
    if name in ("rotateX", "rotateY", "rotateZ", "controlledRotateX",
                "controlledRotateY", "controlledRotateZ"):
        axis = name[-1]
        t = int(view["target"])
        ctrl = _proj((view["control"],)) if name.startswith("controlled") \
            else ()
        op = {"X": ("x", None, (t,)),
              "Y": ("matrix", M.PAULI_Y_M, (t,)),
              "Z": ("diag", (1.0, -1.0), (t,))}[axis]
        post.append(("angle", 1.0, "im", ctrl + (op,), None))
    elif name in ("rotateAroundAxis", "controlledRotateAroundAxis"):
        t = int(view["target"])
        ctrl = _proj((view["control"],)) if name.startswith("controlled") \
            else ()
        gen = _axis_generator(view["axis"])
        post.append(("angle", 1.0, "im",
                     ctrl + (("matrix", gen, (t,)),), None))
    elif name == "multiRotateZ":
        post.append(("angle", 1.0, "im", _zs(view["qubits"]), None))
    elif name == "multiControlledMultiRotateZ":
        post.append(("angle", 1.0, "im",
                     _proj(view["controls"]) + _zs(view["targets"]), None))
    elif name == "multiRotatePauli":
        post.append(("angle", 1.0, "im",
                     _pauli_steps(view["targets"], view["paulis"]), None))
    elif name == "multiControlledMultiRotatePauli":
        post.append(("angle", 1.0, "im",
                     _proj(view["controls"])
                     + _pauli_steps(view["targets"], view["paulis"]), None))
    elif name == "phaseShift":
        post.append(("angle", -2.0, "im", _proj((view["target"],)), None))
    elif name == "controlledPhaseShift":
        post.append(("angle", -2.0, "im",
                     _proj((view["q1"], view["q2"])), None))
    elif name == "multiControlledPhaseShift":
        post.append(("angle", -2.0, "im", _proj(view["qubits"]), None))
    elif name in ("compactUnitary", "controlledCompactUnitary"):
        t = int(view["target"])
        ctrl = _proj((view["control"],)) if name.startswith("controlled") \
            else ()
        pre.extend([
            ("alpha", 2.0, "re", ctrl, "re"),
            ("alpha", -2.0, "im", ctrl + (("diag", (1.0, -1.0), (t,)),),
             "im"),
            ("beta", 2.0, "im", ctrl + (("matrix", M.PAULI_Y_M, (t,)),),
             "re"),
            ("beta", -2.0, "im", ctrl + (("x", None, (t,)),), "im"),
        ])
    else:  # pragma: no cover - guarded by plan_backward
        raise QuESTError(f"no derivative rule for '{name}'", "gradient")
    return tuple(post), tuple(pre)


def _apply_steps(shell: Qureg, steps) -> None:
    for kind, payload, qs in steps:
        if kind == "x":
            G._apply_gate_x(shell, qs)
        elif kind == "diag":
            G._apply_gate_diag(shell, list(payload), qs)
        else:
            G._apply_gate_matrix(shell, payload, qs)


def _bracket(lam_amps, phi_amps, steps, num_qubits, part):
    """Re or Im of ⟨λ|Op|φ⟩ with Op the steps program (identity if empty)."""
    if steps:
        shell = Qureg(num_qubits, False, phi_amps, env=None)
        _apply_steps(shell, steps)
        phi_amps = shell.amps
    re, im = R.inner_product(lam_amps, phi_amps)
    return re if part == "re" else im


# ---------------------------------------------------------------------------
# exact daggers
# ---------------------------------------------------------------------------

def _dagger_param(shell: Qureg, name: str, vals: dict) -> None:
    """Apply the entry's exact inverse through its own public gate function
    (traced-angle branches included): angle → -angle for the rotation and
    phase families, (α, β) → (α*, -β) for the compact-unitary family."""
    if name == "compactUnitary":
        G.compactUnitary(shell, vals["target"],
                         jnp.conj(vals["alpha"]), -vals["beta"])
        return
    if name == "controlledCompactUnitary":
        G.controlledCompactUnitary(shell, vals["control"], vals["target"],
                                   jnp.conj(vals["alpha"]), -vals["beta"])
        return
    fields = _FIELDS[name]
    args = [vals[f] for f in fields]
    args[fields.index("angle")] = -vals["angle"]
    getattr(G, name)(shell, *args)


def _apply_event_dagger(shell: Qureg, ev) -> None:
    """Invert one captured GateEvent through the scheduler-aware helpers:
    :func:`..fusion.event_dagger` builds the inverse event, applied here
    by kind."""
    from ..fusion import event_dagger

    try:
        inv = event_dagger(ev)
    except ValueError as e:  # pragma: no cover - guarded by plan_backward
        raise QuESTError(str(e), "gradient") from None
    if inv.kind == "matrix":
        G._apply_gate_matrix(shell, inv.matrix, inv.targets,
                             inv.controls, inv.states)
    elif inv.kind == "diag":
        G._apply_gate_diag(shell, inv.diag, inv.targets, inv.controls)
    elif inv.kind == "x":
        G._apply_gate_x(shell, inv.targets, inv.controls, inv.states)
    elif inv.kind == "parity":
        G._apply_gate_parity_phase(shell, inv.theta, inv.targets,
                                   inv.controls)
    elif inv.kind == "swap":
        G.swapGate(shell, inv.targets[0], inv.targets[1])
    else:  # pragma: no cover - event_dagger returns unitary kinds only
        raise QuESTError(f"cannot apply '{inv.kind}' event", "gradient")


# ---------------------------------------------------------------------------
# backward plan
# ---------------------------------------------------------------------------

@dataclass(frozen=True)
class _EntryPlan:
    name: str
    param: bool
    view: Optional[tuple] = None      # ((field, template-value), ...)
    post: tuple = ()
    pre: tuple = ()
    events: tuple = ()                # captured GateEvents (concrete entry)


def _site(idx, name):
    return f"tape[{idx}]:{name}"


def _capture_events(fn, args, kwargs, idx, name, num_qubits, dtype):
    """Concrete entry -> invertible GateEvents, or a typed lift-time error
    naming the site."""
    from .. import fusion

    if name == "_apply_dense_block":
        u, qubits = args
        return (fusion.GateEvent("matrix", tuple(qubits),
                                 matrix=np.asarray(u)),)
    if name == "_apply_gate_diag":
        diag, qubits = args[0], args[1]
        return (fusion.GateEvent("diag", tuple(qubits),
                                 diag=np.asarray(diag)),)
    if name in ("_apply_pallas_run", "_apply_frame_swap"):
        raise QuESTError(
            f"Circuit.gradient: {_site(idx, name)} is a pallas-fused plan "
            "entry with no gate-by-gate inverse; differentiate the raw "
            "(unfused) circuit -- the gradient program is one jitted "
            "dispatch either way", "gradient")
    events = fusion.capture(fn, args, kwargs, num_qubits, dtype)
    if events is None or any(ev.kind in ("channel", "aux") or ev.extended
                             for ev in events):
        hint = (" -- compose measurement statistics via sample_request "
                "instead of differentiating through them"
                if ("easure" in name or "collapse" in name.lower())
                else "")
        raise QuESTError(
            f"Circuit.gradient: {_site(idx, name)} is not invertible, so "
            f"the adjoint backward sweep cannot cross it{hint}", "gradient")
    return tuple(events)


#: plan/reduce caches key on the LiftedTape's identity (entry kwargs make
#: it unhashable); the cached value keeps the tape alive so ids are stable.
#: Circuits memoize their lifted tape per revision, so this deduplicates
#: exactly like an lru would.
_PLAN_CACHE: dict = {}
_REDUCE_CACHE: dict = {}


def _plan_cached(lifted, num_qubits, dtype_str):
    key = (id(lifted), num_qubits, dtype_str)
    hit = _PLAN_CACHE.get(key)
    if hit is not None:
        return hit[1], hit[2]
    plans, stop = _plan_build(lifted, num_qubits, dtype_str)
    _PLAN_CACHE[key] = (lifted, plans, stop)
    return plans, stop


def _plan_build(lifted, num_qubits, dtype_str):
    entries = lifted.entries
    plans = [None] * len(entries)
    first_slot = None
    for idx, (fn, args, kwargs) in enumerate(entries):
        name = getattr(fn, "__name__", str(fn))
        refs = _slot_refs(args, kwargs)
        if name in _FIELDS:
            view = _entry_view(name, args, kwargs)
            post, pre = _rules(name, view)
            plans[idx] = _EntryPlan(name, True, tuple(view.items()),
                                    post, pre)
            if first_slot is None:
                first_slot = idx
        elif refs:
            # a slot outside the differentiable families is a stochastic
            # seed (trajectory Kraus / mid-circuit measurement)
            hint = ("mid-circuit measurement"
                    if name == "applyMidMeasurement"
                    else "trajectory noise")
            raise QuESTError(
                f"Circuit.gradient: {_site(idx, name)} is a {hint} site -- "
                "an undifferentiable stochastic seam; compose it via "
                "sample_request instead of differentiating through it",
                "gradient")
        else:
            plans[idx] = (fn, args, kwargs, name)  # resolved below
    if first_slot is None:
        raise QuESTError(
            "Circuit.gradient: tape has no differentiable parameter slots "
            "(no rotation/phase/compact-unitary entries)", "gradient")
    # entries before the first slot are the effective initial state (state
    # preps included) -- the backward walk never crosses them, so they need
    # no inverse; everything after must be invertible
    dtype = np.dtype(dtype_str)
    for idx in range(first_slot + 1, len(entries)):
        if isinstance(plans[idx], _EntryPlan):
            continue
        fn, args, kwargs, name = plans[idx]
        events = _capture_events(fn, args, kwargs, idx, name,
                                 num_qubits, dtype)
        plans[idx] = _EntryPlan(name, False, events=events)
    return tuple(plans[first_slot:]), first_slot


def plan_backward(lifted, num_qubits: int, dtype=None):
    """``(plans, stop)``: per-entry backward plans for entries ``stop..P-1``
    (``stop`` = first slot-bearing entry; the prefix is the effective
    initial state). Raises a typed :class:`QuESTError` naming the first
    non-invertible site."""
    dt = np.dtype(dtype if dtype is not None else jnp.result_type(float))
    return _plan_cached(lifted, num_qubits, dt.str)


def check_differentiable(circuit, dtype=None) -> int:
    """Satellite audit entry point: validate every tape item is adjoint-
    differentiable, returning the slot count. Typed QuESTError (offending
    site named) otherwise."""
    if circuit.is_density_matrix:
        raise QuESTError(
            "Circuit.gradient: density-matrix tapes are not supported by "
            "the adjoint sweep (⟨λ|∂G|φ⟩ needs pure states); use a "
            "statevector register", "gradient")
    lifted = circuit.lifted()
    plan_backward(lifted, circuit.num_qubits, dtype)
    return len(lifted.slots)


# ---------------------------------------------------------------------------
# the reduce: forward value + backward sweep, one traceable program
# ---------------------------------------------------------------------------

def _accumulate(grads, ref, g, comp):
    idx = ref.index
    if comp == "im":
        g = (_CPLX_IM * 1j) * g
    cur = grads[idx]
    grads[idx] = g if cur is None else cur + g


def _cached_reduce(lifted, num_qubits, codes, coeffs, dtype_str):
    key = (id(lifted), num_qubits, codes, coeffs, dtype_str)
    hit = _REDUCE_CACHE.get(key)
    if hit is not None:
        return hit[1]
    plans, stop = _plan_cached(lifted, num_qubits, dtype_str)
    slots = lifted.slots
    slot_count = len(slots)

    def grad_fn(amps, values):
        lam = apply_hamiltonian(amps, codes=codes, coeffs=coeffs,
                                num_qubits=num_qubits)
        value = expectation_value(amps, lam)
        grads = [None] * slot_count
        phi = Qureg(num_qubits, False, amps, env=None)
        lamq = Qureg(num_qubits, False, lam, env=None)
        for plan in reversed(plans):
            if plan.param:
                view = dict(plan.view)
                vals = {f: (values[v.index] if isinstance(v, _SlotRef)
                            else v) for f, v in view.items()}
                for field, coef, part, steps, comp in plan.post:
                    g = coef * _bracket(lamq.amps, phi.amps, steps,
                                        num_qubits, part)
                    _accumulate(grads, view[field], g, comp)
                _dagger_param(phi, plan.name, vals)
                for field, coef, part, steps, comp in plan.pre:
                    g = coef * _bracket(lamq.amps, phi.amps, steps,
                                        num_qubits, part)
                    _accumulate(grads, view[field], g, comp)
                _dagger_param(lamq, plan.name, vals)
            else:
                for ev in reversed(plan.events):
                    _apply_event_dagger(phi, ev)
                for ev in reversed(plan.events):
                    _apply_event_dagger(lamq, ev)
        slot_grads = tuple(
            g if g is not None else jnp.real(values[i]) * 0.0
            for i, g in enumerate(grads))
        named = {}
        for s, g in zip(slots, slot_grads):
            if s.name is not None:
                named[s.name] = named[s.name] + g if s.name in named else g
        return {"value": value, "grads": named, "slot_grads": slot_grads}

    grad_fn.wants_values = True
    grad_fn.dispatch_route = "grad_request"
    grad_fn.num_slots = slot_count
    grad_fn.hamiltonian = (codes, coeffs)
    _REDUCE_CACHE[key] = (lifted, grad_fn)
    return grad_fn


def grad_reduce(circuit, hamiltonian, *, dtype=None):
    """The values-aware finalize lowering a circuit's adjoint gradient into
    its parameterized replay: ``reduce(ψ, values) -> {"value", "grads",
    "slot_grads"}``. Cached per (tape structure, Hamiltonian, dtype) so
    warm optimizer loops share one compiled program (zero retraces)."""
    codes, coeffs = hamiltonian_terms(hamiltonian, circuit.num_qubits)
    check_differentiable(circuit, dtype)
    dt = np.dtype(dtype if dtype is not None else jnp.result_type(float))
    return _cached_reduce(circuit.lifted(), circuit.num_qubits,
                          codes, coeffs, dt.str)


# ---------------------------------------------------------------------------
# host-facing executable
# ---------------------------------------------------------------------------

class GradExecutable:
    """A compiled gradient program bound to one circuit's slot layout.

    ``__call__(amps, params)`` runs forward + backward + accumulation as
    ONE device dispatch (``device_dispatch_total{route="grad_request"}``)
    and returns ``{"value", "grads", "slot_grads"}``.
    """

    def __init__(self, ex, reduce_fn):
        self._ex = ex
        self._reduce = reduce_fn
        self.lifted = ex.lifted
        self.fingerprint = ex.fingerprint

    @property
    def param_names(self):
        return self._ex.param_names

    @property
    def num_slots(self):
        return self._reduce.num_slots

    def bind(self, params=None):
        return self._ex.bind(params)

    def with_values(self, amps, values):
        telemetry.inc("grad_requests_total")
        telemetry.inc("grad_slots_total", self._reduce.num_slots)
        telemetry.inc("device_dispatch_total", route="grad_request")
        return self._ex.with_values(amps, values)

    def __call__(self, amps, params=None):
        return self.with_values(amps, self.bind(params))


def gradient_executable(circuit, hamiltonian, *, donate=True, dtype=None):
    """Compile ``circuit``'s adjoint gradient against a Pauli-sum
    Hamiltonian -- the implementation behind :meth:`Circuit.gradient`."""
    reduce_fn = grad_reduce(circuit, hamiltonian, dtype=dtype)
    ex = circuit.parameterized(donate=donate, reduce=reduce_fn)
    return GradExecutable(ex, reduce_fn)
