"""Adjoint-mode gradient engine: variational traffic as first-class
requests (ROADMAP item 3; Jones & Gacon, arXiv:2009.02823).

- :mod:`.adjoint` -- the reverse sweep itself: ``grad_reduce`` lowers
  forward + backward + per-slot accumulation into one values-aware reduce
  the replay/batcher compose into a single ``route=grad_request`` program;
  ``gradient_executable`` is the host-facing compile (``Circuit.gradient``).
- :mod:`.expectation` -- Pauli-sum Hamiltonian normalisation and the
  λ = H|ψ⟩ costate build, scheduler-aware.
- :mod:`.shift` -- parameter-shift rules, the independent correctness
  oracle (2-4 replays per parameter; never the serving path).

Serving entry points live on the engine: ``Engine.submit_grad(params)``
batches T optimizer chains into one vmapped gradient program,
``EnginePool.submit_grad`` routes them fleet-wide.
"""

from .adjoint import (GradExecutable, check_differentiable, grad_reduce,
                      gradient_executable, plan_backward)
from .expectation import apply_hamiltonian, expectation_value, hamiltonian_terms
from .shift import parameter_shift

__all__ = [
    "GradExecutable", "check_differentiable", "grad_reduce",
    "gradient_executable", "plan_backward", "apply_hamiltonian",
    "expectation_value", "hamiltonian_terms", "parameter_shift",
]
