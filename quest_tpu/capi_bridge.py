"""Bridge between the native C API shim (native/src/capi.cpp) and the core.

The C layer embeds CPython, imports this module once, and funnels every API
call through it. Objects with identity (env, Qureg, DiagonalOp) live in
handle registries here — the C structs carry only an int handle plus
value-type mirror fields — while value-like operands (matrices, Pauli
strings, SubDiagonalOps) are marshalled per call.

The reference keeps its whole runtime in C (QuEST.c -> backends); here the
C runtime is a thin dispatch veneer and the engine is the JAX/XLA core, so
a reference user program gets TPU execution from an unchanged .c file.
"""

from __future__ import annotations

import itertools

import numpy as np

import quest_tpu as qt
from . import datatypes

_HANDLES: dict[int, object] = {}
_NEXT = itertools.count(1)


def _register(obj) -> int:
    h = next(_NEXT)
    _HANDLES[h] = obj
    return h


def ref(handle: int):
    """Resolve a C-side handle to its live core object."""
    return _HANDLES[handle]


def drop(handle: int) -> None:
    _HANDLES.pop(handle, None)


# ------------------------------------------------------------------- env --

def env_create():
    env = qt.createQuESTEnv()
    return _register(env), env.rank, env.num_ranks, list(qt.getQuESTSeeds(env))


def env_destroy(handle: int) -> None:
    qt.destroyQuESTEnv(ref(handle))
    drop(handle)


def env_seed(handle: int, seeds) -> list:
    qt.seedQuEST(ref(handle), [int(s) for s in seeds])
    return list(qt.getQuESTSeeds(ref(handle)))


def env_seed_default(handle: int) -> list:
    qt.seedQuESTDefault(ref(handle))
    return list(qt.getQuESTSeeds(ref(handle)))


# ----------------------------------------------------------------- qureg --

def qureg_create(num_qubits: int, env_handle: int, is_density: bool):
    env = ref(env_handle)
    make = qt.createDensityQureg if is_density else qt.createQureg
    q = make(num_qubits, env)
    return _register(q), q.num_qubits_in_state_vec, q.num_amps_total


def qureg_clone(src_handle: int, env_handle: int):
    q = qt.createCloneQureg(ref(src_handle), ref(env_handle))
    return _register(q), q.num_qubits_in_state_vec, q.num_amps_total


def qureg_destroy(handle: int) -> None:
    qt.destroyQureg(ref(handle))
    drop(handle)


def _f64(buf) -> np.ndarray:
    """Bulk data crosses the C boundary as raw float64 bytes, not lists."""
    return np.frombuffer(buf, dtype=np.float64)


def qureg_pull(handle: int, start: int, num: int) -> tuple:
    """(real bytes, imag bytes) of amplitudes [start, start+num), float64."""
    q = ref(handle)
    mirror = qt.copySubstateFromGPU(q, start, num)
    block = mirror[:, start:start + num].astype(np.float64)
    return block[0].tobytes(), block[1].tobytes()


def qureg_push(handle: int, start: int, re_b: bytes, im_b: bytes) -> None:
    q = ref(handle)
    re = _f64(re_b)
    q.state_vec[0, start:start + len(re)] = re
    q.state_vec[1, start:start + len(re)] = _f64(im_b)
    qt.copySubstateToGPU(q, start, len(re))


def set_amps(handle: int, start: int, re_b: bytes, im_b: bytes) -> None:
    re = _f64(re_b)
    qt.setAmps(ref(handle), start, re, _f64(im_b), len(re))


def set_density_amps(handle: int, row: int, col: int, re_b: bytes, im_b: bytes) -> None:
    re = _f64(re_b)
    qt.setDensityAmps(ref(handle), row, col, re, _f64(im_b), len(re))


def init_state_from_amps(handle: int, re_b: bytes, im_b: bytes) -> None:
    qt.initStateFromAmps(ref(handle), _f64(re_b), _f64(im_b))


def prob_all_outcomes(handle: int, qubits) -> bytes:
    probs = qt.calcProbOfAllOutcomes(ref(handle), list(qubits))
    return np.asarray(probs, dtype=np.float64).tobytes()


# ------------------------------------------------------------- operators --

def make_hamil(num_qubits: int, codes, coeffs) -> datatypes.PauliHamil:
    h = qt.createPauliHamil(num_qubits, len(coeffs))
    qt.initPauliHamil(h, [float(c) for c in coeffs], [int(c) for c in codes])
    return h


def parse_hamil_file(fn: str):
    h = qt.createPauliHamilFromFile(fn)
    return (h.num_qubits, h.num_sum_terms,
            [int(c) for c in np.ravel(h.pauli_codes)],
            [float(c) for c in h.term_coeffs])


def make_subdiag(num_qubits: int, re_b: bytes, im_b: bytes) -> datatypes.SubDiagonalOp:
    op = qt.createSubDiagonalOp(num_qubits)
    op.elems[...] = _f64(re_b) + 1j * _f64(im_b)
    return op


def diag_create(num_qubits: int, env_handle: int):
    op = qt.createDiagonalOp(num_qubits, ref(env_handle))
    return _register(op), (1 << num_qubits)


def diag_destroy(handle: int) -> None:
    qt.destroyDiagonalOp(ref(handle))
    drop(handle)


def diag_set(handle: int, start: int, re_b: bytes, im_b: bytes) -> None:
    re = _f64(re_b)
    qt.setDiagonalOpElems(ref(handle), start, re, _f64(im_b), len(re))


def _diag_elems(op) -> tuple:
    elems = np.asarray(op.elems, dtype=np.float64)
    return elems[0].tobytes(), elems[1].tobytes()


def diag_from_hamil(handle: int, num_qubits: int, codes, coeffs) -> tuple:
    """initDiagonalOpFromPauliHamil + pull elems back for the C host mirror."""
    op = ref(handle)
    qt.initDiagonalOpFromPauliHamil(op, make_hamil(num_qubits, codes, coeffs))
    return _diag_elems(op)


def diag_from_file(fn: str, env_handle: int):
    op = qt.createDiagonalOpFromPauliHamilFile(fn, ref(env_handle))
    re_b, im_b = _diag_elems(op)
    return _register(op), op.num_qubits, re_b, im_b


def calc_expec_diag(qureg_handle: int, diag_handle: int) -> complex:
    return complex(qt.calcExpecDiagonalOp(ref(qureg_handle), ref(diag_handle)))


# ---------------------------------------------------------------- generic --

def call(fname: str, *args):
    """Invoke a top-level quest_tpu function with pre-resolved arguments."""
    return getattr(qt, fname)(*args)
