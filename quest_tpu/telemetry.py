"""Engine flight recorder: one metrics spine for every layer.

The reference simulator ships no timers, counters, or trace hooks (SURVEY.md
section 5 -- its only introspection is reportQuregParams and the QASM log),
and until round 6 this build's own perf evidence was scattered across ad-hoc
dicts (scheduler.stats), bench-only printouts (per-pass floors) and silent
fast-path bailouts nobody could see. This module is the single registry all
of them report into and every artifact is derived from:

- **Counters / gauges / histograms**, labeled Prometheus-style
  (``inc("engine_fallback_total", reason="df_tile_mismatch")``) -- the
  fusion planner, the distributed scheduler, the exchange kernels, the
  Pallas dispatch layer and the trajectory noise engine (the
  ``trajectory_*`` series: channel sites unraveled per kind, trajectories
  run, ensembles driven -- docs/trajectories.md) all record here (see the
  instrumentation map in docs/observability.md).
- **Nested host-side spans** with monotonic timing
  (``with span("fusion.plan", qubits=26): ...``): each completed span
  aggregates into the registry (count / total_s / max_s) and, optionally,
  streams one JSONL event (``QUEST_TELEMETRY_JSONL=/path`` or
  :func:`export_jsonl`).
- **Snapshots**: :func:`snapshot` returns the whole registry as one nested
  JSON-ready dict -- ``bench.py`` embeds it in ``BENCH_DETAIL.json`` so the
  per-pass / comm-volume / fallback story ships with every headline number.
- **Request traces** (round 17): a :class:`TraceContext`
  (trace_id / span_id / parent_id) minted at ``Engine.submit`` /
  ``EnginePool.submit`` and propagated across every thread hop of the
  serving path, with causal span links for hedges, failovers, retries and
  bisection halves. Each request accumulates the canonical :data:`PHASES`
  vector (``queue_wait``/``coalesce``/``cache_lookup``/``compile``/
  ``dispatch``/``device``/``resolve``) into ``request_phase_ms{phase}``
  histograms (p50/p95/p99 in :func:`snapshot`), and completed traces
  export as Perfetto-loadable Chrome trace JSON
  (:func:`export_chrome_trace`, ``tools/traceview.py``). Sampling is
  head-based via ``QUEST_TRACE=off|errors|<rate>|all`` (malformed values
  warn once as QT701); errored requests are always captured; the off
  path is one boolean read (:func:`trace_on`), same contract as
  :func:`span`.
- **Async serving series** (round 18): the completion-ring engine
  reports ``engine_async_inflight`` (gauge: ring occupancy after every
  admit / retire) and
  ``engine_async_retires_total{outcome=ok|hang|integrity|error}`` (one
  per retired in-flight batch, through the same corrupt / sentinel /
  trace gates as a synchronous dispatch); the pool's ahead-of-demand
  compiler counts ``engine_precompile_total{outcome=warmed|cached|
  error}``; whole-request chaining launches exactly one program per
  request -- ``device_dispatch_total{route="request"}``, the round-18
  dispatch floor (docs/serving.md).

Semantics notes:

- Everything here is HOST-side accounting. Inside ``jax.jit`` the
  instrumented code runs once per *trace*, so counters count traced work
  (plan shape, comm chunk-units of the compiled program), not per-execution
  device work; span durations around jitted calls measure dispatch (plus
  compilation on the first call), not device drain.
- **Zero overhead when disabled**: ``QUEST_TELEMETRY=0`` rebinds the whole
  public surface to no-op stubs at import (a disabled process records
  nothing and allocates nothing). In-process, :func:`disabled` flips the
  same guard temporarily -- tests use it to assert bit-identical results.
- Thread-safe: one lock around the registry maps, a thread-local span
  stack, so instrumented code may run from any thread.
"""

from __future__ import annotations

import contextlib
import json
import os
import sys
import threading
import time

__all__ = [
    "enabled", "disabled", "inc", "set_gauge", "observe", "span", "event",
    "counter_value", "counter_total", "counters", "snapshot", "reset",
    "export_jsonl", "events",
    "PHASES", "TraceContext", "trace_on", "trace_mode", "trace_policy",
    "start_trace", "finish_trace", "current_trace", "current_traces",
    "set_current_trace", "clear_current_trace", "trace_event_current",
    "traces", "trace_thread_leaks", "export_chrome_trace", "export_traces",
    "chrome_trace_events",
]

#: import-time master switch; QUEST_TELEMETRY=0 swaps in the no-op stubs
_ENV_ENABLED = os.environ.get("QUEST_TELEMETRY", "1").strip().lower() \
    not in ("0", "false", "off")

#: if set, every completed span / event streams one JSON line here
_JSONL_ENV = "QUEST_TELEMETRY_JSONL"

#: default cap on the in-memory event ring (oldest dropped first,
#: counted in ``telemetry_events_dropped_total``): a flight recorder must
#: never grow without bound inside a long-lived server. Overridable via
#: QUEST_TELEMETRY_EVENTS_MAX (parsed lazily at first event; QT303
#: warn-once on malformed values).
_MAX_EVENTS = 1 << 16
_EVENTS_MAX_ENV = "QUEST_TELEMETRY_EVENTS_MAX"
_EVENTS_MAX_WARNED: set = set()

#: the canonical per-request phase vector (docs/observability.md): every
#: finished trace carries all seven keys (0.0 when a phase never ran)
PHASES = ("queue_wait", "coalesce", "cache_lookup", "compile",
          "dispatch", "device", "resolve")

#: head-based trace sampling knob: off | errors | <rate in (0,1)> | all
_TRACE_ENV = "QUEST_TRACE"
_TRACE_WARNED: set = set()

#: cap on retained finished traces (oldest dropped first)
_MAX_TRACES = 4096

#: per-series reservoir cap backing the p50/p95/p99 snapshot rollups
_SAMPLE_CAP = 8192


def _label_key(labels: dict) -> str:
    """Canonical ``{k=v,...}`` suffix (sorted keys; '' when unlabeled)."""
    if not labels:
        return ""
    items = ",".join(f"{k}={labels[k]}" for k in sorted(labels))
    return "{" + items + "}"


def _series_key(name: str, labels: dict) -> str:
    return name + _label_key(labels)


class _SpanHandle:
    """One live span: context manager recording a monotonic duration into
    the registry on exit (and one JSONL event). Nesting is tracked via the
    registry's thread-local stack; ``path`` is the '/'-joined ancestry."""

    __slots__ = ("_reg", "name", "labels", "_t0", "path", "duration_s")

    def __init__(self, reg: "MetricsRegistry", name: str, labels: dict):
        self._reg = reg
        self.name = name
        self.labels = labels
        self._t0 = 0.0
        self.path = name
        self.duration_s = None

    def __enter__(self):
        stack = self._reg._span_stack()
        if stack:
            self.path = stack[-1].path + "/" + self.name
        stack.append(self)
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, exc_type, exc, tb):
        self.duration_s = time.perf_counter() - self._t0
        stack = self._reg._span_stack()
        if stack and stack[-1] is self:
            stack.pop()
        self._reg._finish_span(self)
        return False


class _NullSpan:
    """Shared no-op span for the disabled path (no allocation per call)."""

    __slots__ = ()
    duration_s = None
    path = ""

    def __enter__(self):
        return self

    def __exit__(self, exc_type, exc, tb):
        return False


_NULL_SPAN = _NullSpan()


def _registry_lock():
    """The registry's lock from the instrumented sync layer
    (``resilience.sync``, name ``telemetry.registry``, ``record=False``
    so recording a metric never records a metric). Telemetry sits below
    everything, so the layer is probed via sys.modules instead of
    imported: at bootstrap (sync itself imports telemetry first) this
    falls back to a raw lock, which sync adopts at ITS import."""
    sync = sys.modules.get(__name__.rsplit(".", 1)[0] + ".resilience.sync")
    if sync is not None:
        return sync.Lock("telemetry.registry", record=False)
    return threading.Lock()  # concheck: allow-raw-lock (bootstrap only)


class MetricsRegistry:
    """Process-global metric store; all module-level helpers delegate to
    one shared instance (:data:`REGISTRY`)."""

    def __init__(self):
        self._lock = _registry_lock()
        self._local = threading.local()
        self.enabled = _ENV_ENABLED
        self._jsonl_fh = None
        self._jsonl_path = os.environ.get(_JSONL_ENV)
        #: event-ring cap; resolved lazily at the first append so the
        #: QT303 diagnostic (which imports analysis.diagnostics, which
        #: imports this module) never runs during telemetry bootstrap
        self._events_max: int | None = None
        self._reset_locked()

    # -- storage ------------------------------------------------------------

    def _reset_locked(self):
        self._counters: dict[str, float] = {}
        self._gauges: dict[str, float] = {}
        self._hists: dict[str, dict] = {}
        self._spans: dict[str, dict] = {}
        self._events: list[dict] = []
        self._events_dropped = 0
        #: bounded raw-sample reservoirs backing snapshot percentiles,
        #: series-keyed like _hists (only observe_sampled series get one)
        self._samples: dict[str, list] = {}
        #: retained finished request traces (JSON-ready dicts)
        self._traces: list[dict] = []
        #: thread ident -> (thread name, live TraceContext tuple): the
        #: QT703 leak scan reads this (a pooled thread that still holds a
        #: finished trace after future resolution leaked its context)
        self._thread_traces: dict[int, tuple] = {}

    def reset(self) -> None:
        """Drop every recorded metric and event (tests, bench sections)."""
        with self._lock:
            self._reset_locked()

    def _span_stack(self) -> list:
        stack = getattr(self._local, "stack", None)
        if stack is None:
            stack = self._local.stack = []
        return stack

    # -- recording ----------------------------------------------------------

    def inc(self, name: str, value: float = 1.0, **labels) -> None:
        """Add ``value`` to the counter series ``name{labels}``."""
        if not self.enabled:
            return
        key = _series_key(name, labels)
        with self._lock:
            self._counters[key] = self._counters.get(key, 0.0) + value

    def set_gauge(self, name: str, value: float, **labels) -> None:
        """Set the gauge series ``name{labels}`` to ``value``."""
        if not self.enabled:
            return
        key = _series_key(name, labels)
        with self._lock:
            self._gauges[key] = float(value)

    def observe(self, name: str, value: float, **labels) -> None:
        """Record one observation into the histogram ``name{labels}``
        (count / sum / min / max aggregate -- enough to derive rates and
        spot outliers without shipping raw samples)."""
        if not self.enabled:
            return
        key = _series_key(name, labels)
        v = float(value)
        with self._lock:
            h = self._hists.get(key)
            if h is None:
                self._hists[key] = {"count": 1, "sum": v, "min": v, "max": v}
            else:
                h["count"] += 1
                h["sum"] += v
                h["min"] = min(h["min"], v)
                h["max"] = max(h["max"], v)

    def observe_sampled(self, name: str, value: float, **labels) -> None:
        """:meth:`observe`, plus the raw value lands in a bounded
        per-series reservoir (sliding window of the last ``_SAMPLE_CAP``)
        so :meth:`snapshot` can report p50/p95/p99 for this series. Used
        for the SLO rollup series (``request_phase_ms{phase}``); ordinary
        histograms stay count/sum/min/max."""
        if not self.enabled:
            return
        key = _series_key(name, labels)
        v = float(value)
        with self._lock:
            h = self._hists.get(key)
            if h is None:
                h = self._hists[key] = {"count": 1, "sum": v,
                                        "min": v, "max": v}
            else:
                h["count"] += 1
                h["sum"] += v
                h["min"] = min(h["min"], v)
                h["max"] = max(h["max"], v)
            s = self._samples.get(key)
            if s is None:
                s = self._samples[key] = []
            if len(s) < _SAMPLE_CAP:
                s.append(v)
            else:
                s[(h["count"] - 1) % _SAMPLE_CAP] = v

    def span(self, name: str, **labels):
        """Context manager timing a nested host-side region."""
        if not self.enabled:
            return _NULL_SPAN
        return _SpanHandle(self, name, labels)

    def event(self, name: str, **fields) -> None:
        """Append one raw flight-recorder event (JSONL-exportable)."""
        if not self.enabled:
            return
        self._append_event({"kind": "event", "name": name, "t": time.time(),
                            **fields})

    def _finish_span(self, sp: _SpanHandle) -> None:
        key = _series_key(sp.name, sp.labels)
        with self._lock:
            agg = self._spans.get(key)
            if agg is None:
                self._spans[key] = {"count": 1, "total_s": sp.duration_s,
                                    "max_s": sp.duration_s}
            else:
                agg["count"] += 1
                agg["total_s"] += sp.duration_s
                agg["max_s"] = max(agg["max_s"], sp.duration_s)
        self._append_event({"kind": "span", "name": sp.name, "t": time.time(),
                            "path": sp.path, "dur_s": round(sp.duration_s, 9),
                            **({"labels": sp.labels} if sp.labels else {})})

    def _events_cap(self) -> int:
        """The ring cap, parsed from QUEST_TELEMETRY_EVENTS_MAX on first
        use (outside the registry lock: the QT303 warn-once path records
        a finding counter, which takes it)."""
        cap = self._events_max
        if cap is None:
            cap = _MAX_EVENTS
            if os.environ.get(_EVENTS_MAX_ENV, "").strip():
                try:
                    from .analysis.diagnostics import parse_env_int
                    cap = parse_env_int(
                        _EVENTS_MAX_ENV, _MAX_EVENTS, minimum=1,
                        code="QT303", warned=_EVENTS_MAX_WARNED,
                        noun="telemetry event-buffer cap")
                except ImportError:  # pragma: no cover - bootstrap only
                    pass
            self._events_max = cap
        return cap

    def _append_event(self, ev: dict) -> None:
        cap = self._events_cap()
        with self._lock:
            self._events.append(ev)
            drop = len(self._events) - cap
            if drop > 0:
                del self._events[:drop]
                self._events_dropped += drop
                key = "telemetry_events_dropped_total"
                self._counters[key] = self._counters.get(key, 0.0) + drop
        path = self._jsonl_path
        if path:
            self._stream_jsonl(ev, path)

    def _stream_jsonl(self, ev: dict, path: str) -> None:
        try:
            if self._jsonl_fh is None:
                self._jsonl_fh = open(path, "a", buffering=1)
            self._jsonl_fh.write(json.dumps(ev) + "\n")
        except OSError:  # a broken sink must never take the engine down
            self._jsonl_path = None

    # -- reading ------------------------------------------------------------

    def counter_value(self, name: str, **labels) -> float:
        """Value of one exact counter series (0.0 if never incremented)."""
        with self._lock:
            return self._counters.get(_series_key(name, labels), 0.0)

    def counter_total(self, name: str) -> float:
        """Sum of a counter across ALL label series."""
        prefix = name + "{"
        with self._lock:
            return sum(v for k, v in self._counters.items()
                       if k == name or k.startswith(prefix))

    def counters(self, name: str) -> dict:
        """{label-suffix: value} for every series of ``name`` ('' when
        unlabeled) -- the per-reason breakdown tests assert against."""
        prefix = name + "{"
        out = {}
        with self._lock:
            for k, v in self._counters.items():
                if k == name:
                    out[""] = v
                elif k.startswith(prefix):
                    out[k[len(name):]] = v
        return out

    def snapshot(self, prefix: str | None = None) -> dict:
        """The whole registry as one JSON-ready dict; ``prefix`` filters
        series names. Histogram/span sums are rounded to keep artifacts
        compact and diff-stable."""
        def keep(k):
            return prefix is None or k.startswith(prefix)

        def num(v):
            return int(v) if float(v).is_integer() else round(v, 6)

        def hist(k, h):
            out = {"count": h["count"], "sum": round(h["sum"], 6),
                   "min": round(h["min"], 6), "max": round(h["max"], 6)}
            s = self._samples.get(k)
            if s:  # percentile rollups only for reservoir-backed series
                arr = sorted(s)
                for q, lbl in ((0.50, "p50"), (0.95, "p95"), (0.99, "p99")):
                    out[lbl] = round(
                        arr[min(len(arr) - 1, int(q * len(arr)))], 6)
            return out

        with self._lock:
            return {
                "counters": {k: num(v)
                             for k, v in sorted(self._counters.items())
                             if keep(k)},
                "gauges": {k: round(v, 6)
                           for k, v in sorted(self._gauges.items())
                           if keep(k)},
                "histograms": {
                    k: hist(k, h)
                    for k, h in sorted(self._hists.items()) if keep(k)},
                "spans": {
                    k: {"count": a["count"],
                        "total_s": round(a["total_s"], 6),
                        "max_s": round(a["max_s"], 6)}
                    for k, a in sorted(self._spans.items()) if keep(k)},
            }

    def events(self) -> list:
        """A copy of the in-memory event ring (most recent last)."""
        with self._lock:
            return list(self._events)

    def export_jsonl(self, path: str, clear: bool = False) -> int:
        """Write every buffered event as one JSON line each; returns the
        number of lines written. ``clear`` drops the buffer afterwards.
        When the ring dropped events (buffer cap, satellite of round 17)
        a leading ``{"kind": "meta", ...}`` line reports how many, so a
        consumer can tell a quiet server from a saturated ring."""
        with self._lock:
            evs = list(self._events)
            dropped = self._events_dropped
            if clear:
                self._events = []
        if dropped:
            evs.insert(0, {"kind": "meta", "events_dropped": dropped,
                           "events_max": self._events_cap()})
        with open(path, "w") as fh:
            for ev in evs:
                fh.write(json.dumps(ev) + "\n")
        return len(evs)


#: the process-global registry every instrumented layer reports into
REGISTRY = MetricsRegistry()


# ---------------------------------------------------------------------------
# module-level convenience surface (what instrumented code imports)
# ---------------------------------------------------------------------------

def enabled() -> bool:
    """True when telemetry is recording (QUEST_TELEMETRY != 0 and not
    inside a :func:`disabled` block)."""
    return REGISTRY.enabled


@contextlib.contextmanager
def disabled():
    """Temporarily disable all recording in-process (tests use this to
    assert the instrumented paths are result-identical without telemetry;
    for true zero-overhead use QUEST_TELEMETRY=0 at process start)."""
    prev = REGISTRY.enabled
    REGISTRY.enabled = False
    try:
        yield
    finally:
        REGISTRY.enabled = prev


def inc(name: str, value: float = 1.0, **labels) -> None:
    REGISTRY.inc(name, value, **labels)


def set_gauge(name: str, value: float, **labels) -> None:
    REGISTRY.set_gauge(name, value, **labels)


def observe(name: str, value: float, **labels) -> None:
    REGISTRY.observe(name, value, **labels)


def span(name: str, **labels):
    return REGISTRY.span(name, **labels)


def event(name: str, **fields) -> None:
    REGISTRY.event(name, **fields)


def counter_value(name: str, **labels) -> float:
    return REGISTRY.counter_value(name, **labels)


def counter_total(name: str) -> float:
    return REGISTRY.counter_total(name)


def counters(name: str) -> dict:
    return REGISTRY.counters(name)


def snapshot(prefix: str | None = None) -> dict:
    return REGISTRY.snapshot(prefix)


def reset() -> None:
    REGISTRY.reset()


def export_jsonl(path: str, clear: bool = False) -> int:
    return REGISTRY.export_jsonl(path, clear)


def events() -> list:
    return REGISTRY.events()


# ---------------------------------------------------------------------------
# request tracing (round 17): causal span trees across the serving fleet
# ---------------------------------------------------------------------------

#: resolved QUEST_TRACE policy: mode in {"off","errors","rate","all"},
#: rate in [0,1]. Resolved lazily on the first trace_on() call so the
#: QT701 diagnostic (analysis.diagnostics imports this module) never runs
#: during telemetry bootstrap; trace_policy() overrides it in-process.
_TRACE_MODE = "off"
_TRACE_RATE = 0.0
_TRACE_RESOLVED = False

#: per-process monotonic trace-id sequence (advanced under REGISTRY._lock)
_TRACE_SEQ = 0


def _parse_trace(raw: str):
    """(mode, rate, error) for one QUEST_TRACE value; error is a human
    fragment when the value is malformed (mode falls back to off)."""
    v = raw.strip().lower()
    if v in ("", "off", "0", "0.0", "false", "none"):
        return "off", 0.0, None
    if v in ("errors", "error"):
        return "errors", 0.0, None
    if v in ("all", "on", "1", "1.0", "true"):
        return "all", 1.0, None
    try:
        rate = float(v)
    except ValueError:
        return "off", 0.0, "is not off|errors|<rate in (0,1)>|all"
    if not 0.0 <= rate <= 1.0:
        return "off", 0.0, f"rate {rate:g} is outside [0, 1]"
    if rate >= 1.0:
        return "all", 1.0, None
    return "rate", rate, None


def _resolve_trace_mode() -> None:
    global _TRACE_MODE, _TRACE_RATE, _TRACE_RESOLVED
    raw = os.environ.get(_TRACE_ENV, "")
    mode, rate, err = _parse_trace(raw)
    if err is not None and raw.strip() not in _TRACE_WARNED:
        _TRACE_WARNED.add(raw.strip())
        try:  # deferred: diagnostics imports telemetry, never the reverse
            import warnings

            from .analysis.diagnostics import emit_findings, make_finding
            f = make_finding(
                "QT701",
                f"{_TRACE_ENV}={raw!r} {err}; tracing stays off",
                f"env:{_TRACE_ENV}")
            emit_findings([f])
            warnings.warn(str(f), RuntimeWarning, stacklevel=4)
        except ImportError:  # pragma: no cover - bootstrap only
            pass
    _TRACE_MODE, _TRACE_RATE, _TRACE_RESOLVED = mode, rate, True


def trace_on() -> bool:
    """True when request tracing is armed. The hot-path contract matches
    :func:`span`: with QUEST_TRACE unset this is one boolean read (after
    a one-time env parse) and every instrumented site bails on it."""
    if not _TRACE_RESOLVED:
        _resolve_trace_mode()
    return _TRACE_MODE != "off" and REGISTRY.enabled


def trace_mode() -> str:
    """The resolved sampling mode: off | errors | rate | all."""
    if not _TRACE_RESOLVED:
        _resolve_trace_mode()
    return _TRACE_MODE


@contextlib.contextmanager
def trace_policy(mode):
    """In-process QUEST_TRACE override (bench phase sections, tests):
    ``with trace_policy("all"): ...`` arms tracing regardless of the
    environment, restoring the prior policy on exit. Raises ValueError
    on a malformed mode (in-process callers get errors, not QT701)."""
    global _TRACE_MODE, _TRACE_RATE, _TRACE_RESOLVED
    m, r, err = _parse_trace(str(mode))
    if err is not None:
        raise ValueError(f"bad trace mode {mode!r}: {err}")
    prev = (_TRACE_MODE, _TRACE_RATE, _TRACE_RESOLVED)
    _TRACE_MODE, _TRACE_RATE, _TRACE_RESOLVED = m, r, True
    try:
        yield
    finally:
        _TRACE_MODE, _TRACE_RATE, _TRACE_RESOLVED = prev


class _Trace:
    """Shared mutable state of one request trace; every
    :class:`TraceContext` handle points at one of these. Mutated only
    under ``REGISTRY._lock``."""

    __slots__ = ("trace_id", "name", "labels", "wall0", "perf0", "spans",
                 "links", "events", "phases", "error", "sampled", "done",
                 "nspans")

    def __init__(self, trace_id, name, labels, wall0, perf0, sampled):
        self.trace_id = trace_id
        self.name = name
        self.labels = labels
        self.wall0 = wall0      # epoch seconds at perf0 (chrome ts base)
        self.perf0 = perf0      # perf_counter origin for span offsets
        self.spans: dict[str, dict] = {}
        self.links: list[dict] = []
        self.events: list[dict] = []
        self.phases: dict[str, float] = {}
        self.error = None
        self.sampled = sampled
        self.done = False
        self.nspans = 0


class TraceContext:
    """A handle onto one span of one request trace.

    Minted by :func:`start_trace` (the root span, ``owns_root=True``) and
    by :meth:`child`; carries ``trace_id`` / ``span_id`` / ``parent_id``
    across thread hops. The layer that minted the root finishes it
    (:func:`finish_trace`); adopted child contexts only :meth:`end` their
    own span. All methods are cheap dict appends under the registry lock
    and are only ever called on the armed path (``trace_on()`` gated)."""

    __slots__ = ("_tr", "span_id", "owns_root")

    def __init__(self, tr: _Trace, span_id: str, owns_root: bool):
        self._tr = tr
        self.span_id = span_id
        self.owns_root = owns_root

    @property
    def trace_id(self) -> str:
        return self._tr.trace_id

    @property
    def parent_id(self):
        sp = self._tr.spans.get(self.span_id)
        return sp["parent"] if sp else None

    @property
    def done(self) -> bool:
        return self._tr.done

    def _add_span(self, name, parent, t0, dur_ms, status, labels,
                  cat=None) -> str:
        tr = self._tr
        with REGISTRY._lock:
            sid = f"s{tr.nspans}"
            tr.nspans += 1
            sp = {"id": sid, "parent": parent, "name": name,
                  "t0_ms": round((t0 - tr.perf0) * 1e3, 6),
                  "dur_ms": dur_ms, "status": status,
                  "thread": threading.current_thread().name}
            if cat:
                sp["cat"] = cat
            if labels:
                sp["labels"] = labels
            tr.spans[sid] = sp
        return sid

    def child(self, name: str, **labels) -> "TraceContext":
        """Open a child span under this one; the returned context must be
        :meth:`end`-ed (a finished trace with an open span is QT702)."""
        sid = self._add_span(name, self.span_id, time.perf_counter(),
                             None, "open", labels)
        return TraceContext(self._tr, sid, False)

    def end(self, status: str = "ok") -> None:
        """Close this context's span (idempotent)."""
        now = time.perf_counter()
        tr = self._tr
        with REGISTRY._lock:
            sp = tr.spans.get(self.span_id)
            if sp is not None and sp["dur_ms"] is None:
                sp["dur_ms"] = round(
                    (now - tr.perf0) * 1e3 - sp["t0_ms"], 6)
                sp["status"] = status

    def record_span(self, name: str, t0: float, dur_s: float,
                    status: str = "ok", **labels) -> str:
        """Record an already-measured closed span (``t0`` from
        ``time.perf_counter()``) under this context; returns its id."""
        return self._add_span(name, self.span_id, t0,
                              round(dur_s * 1e3, 6), status, labels)

    def phase(self, name: str, t0: float, dur_s: float) -> None:
        """Attribute ``dur_s`` to the canonical phase ``name``: the trace's
        phase vector accumulates it AND a closed ``cat="phase"`` span is
        recorded so the waterfall shows where the time sat."""
        tr = self._tr
        ms = dur_s * 1e3
        with REGISTRY._lock:
            tr.phases[name] = tr.phases.get(name, 0.0) + ms
            sid = f"s{tr.nspans}"
            tr.nspans += 1
            tr.spans[sid] = {
                "id": sid, "parent": self.span_id, "name": name,
                "t0_ms": round((t0 - tr.perf0) * 1e3, 6),
                "dur_ms": round(ms, 6), "status": "ok", "cat": "phase",
                "thread": threading.current_thread().name}

    def add_link(self, frm, to, kind: str) -> None:
        """Record a causal link between two spans (hedge duplicate ->
        primary, failover re-dispatch -> failed attempt, retry attempts,
        bisection halves). ``frm``/``to`` are contexts or span ids."""
        fid = frm.span_id if isinstance(frm, TraceContext) else frm
        tid = to.span_id if isinstance(to, TraceContext) else to
        with REGISTRY._lock:
            self._tr.links.append({"from": fid, "to": tid, "kind": kind})

    def link(self, to, kind: str) -> None:
        """:meth:`add_link` from this context's span."""
        self.add_link(self, to, kind)

    def event(self, name: str, **fields) -> None:
        """Append a point event to the trace (rendered as instants)."""
        tr = self._tr
        t_ms = round((time.perf_counter() - tr.perf0) * 1e3, 6)
        with REGISTRY._lock:
            tr.events.append({"name": name, "t_ms": t_ms, "span": self.span_id,
                              **({"fields": fields} if fields else {})})


def start_trace(name: str, t0: float | None = None,
                **labels) -> TraceContext | None:
    """Mint a new request trace and return its root context, or None when
    tracing is off (callers store the None and every later hop skips on
    it). ``t0`` backdates the root to an earlier ``perf_counter`` reading
    (e.g. admission entry) so pre-mint work lands inside the trace.
    Retention is decided at :func:`finish_trace`: mode ``all`` keeps
    everything, ``rate`` keeps a head-based coin flip drawn here, and
    errored requests are always kept (the ``errors`` mode contract)."""
    if not trace_on():
        return None
    global _TRACE_SEQ
    perf = time.perf_counter()
    wall = time.time()
    if t0 is not None:
        wall -= perf - t0
        perf = t0
    if _TRACE_MODE == "all":
        sampled = True
    elif _TRACE_MODE == "rate":
        import random
        sampled = random.random() < _TRACE_RATE
    else:
        sampled = False
    with REGISTRY._lock:
        _TRACE_SEQ += 1
        trace_id = f"{os.getpid():x}-{_TRACE_SEQ:06d}"
    tr = _Trace(trace_id, name, labels, wall, perf, sampled)
    ctx = TraceContext(tr, "s0", True)
    with REGISTRY._lock:
        tr.nspans = 1
        tr.spans["s0"] = {"id": "s0", "parent": None, "name": name,
                          "t0_ms": 0.0, "dur_ms": None, "status": "open",
                          "thread": threading.current_thread().name,
                          **({"labels": labels} if labels else {})}
    return ctx


def finish_trace(ctx: TraceContext | None, error: str | None = None) -> None:
    """Close a trace minted by :func:`start_trace` (idempotent): the root
    span closes, the phase vector is completed to all :data:`PHASES` keys
    and fed into the ``request_phase_ms{phase}`` rollups, and the trace is
    retained (sampled, or ``error`` is set) or discarded."""
    if ctx is None:
        return
    tr = ctx._tr
    now = time.perf_counter()
    with REGISTRY._lock:
        if tr.done:
            return
        tr.done = True
        tr.error = error
        root = tr.spans["s0"]
        if root["dur_ms"] is None:
            root["dur_ms"] = round((now - tr.perf0) * 1e3, 6)
            root["status"] = "error" if error else "ok"
        for p in PHASES:
            tr.phases.setdefault(p, 0.0)
        keep = tr.sampled or error is not None
        if keep:
            REGISTRY._traces.append({
                "trace_id": tr.trace_id, "name": tr.name,
                "labels": tr.labels, "t0": tr.wall0,
                "dur_ms": root["dur_ms"], "error": error,
                "phases_ms": {p: round(v, 6) for p, v in
                              sorted(tr.phases.items())},
                "spans": list(tr.spans.values()),
                "links": list(tr.links), "events": list(tr.events)})
            drop = len(REGISTRY._traces) - _MAX_TRACES
            if drop > 0:
                del REGISTRY._traces[:drop]
        phases = dict(tr.phases)
    for p, ms in phases.items():
        REGISTRY.observe_sampled("request_phase_ms", ms, phase=p)
    REGISTRY.inc("trace_requests_total",
                 outcome="error" if error else
                 ("sampled" if tr.sampled else "unsampled"))


def set_current_trace(ctxs) -> None:
    """Bind the trace context(s) being worked for to the current thread
    (a single context, an iterable, or None/empty to clear). Batchers
    bind the whole batch before dispatch and MUST clear after the futures
    resolve -- a pooled thread still holding finished traces is QT703."""
    if ctxs is None:
        tup = ()
    elif isinstance(ctxs, TraceContext):
        tup = (ctxs,)
    else:
        tup = tuple(c for c in ctxs if c is not None)
    t = threading.current_thread()
    REGISTRY._local.trace = tup
    with REGISTRY._lock:
        if tup:
            REGISTRY._thread_traces[t.ident] = (t.name, tup)
        else:
            REGISTRY._thread_traces.pop(t.ident, None)


def clear_current_trace() -> None:
    """Unbind this thread's trace context(s) (see QT703)."""
    set_current_trace(None)


def current_trace() -> TraceContext | None:
    """The innermost trace context bound to this thread, if any."""
    cur = getattr(REGISTRY._local, "trace", ())
    return cur[-1] if cur else None


def current_traces() -> tuple:
    """All trace contexts bound to this thread (a dispatching batcher
    works for every traced request in the batch at once)."""
    return getattr(REGISTRY._local, "trace", ())


def trace_event_current(name: str, **fields) -> None:
    """Record a point event on every trace bound to this thread (retry
    attempts, degrades): no-op when nothing is bound."""
    for ctx in current_traces():
        ctx.event(name, **fields)


def trace_thread_leaks() -> list:
    """(thread_name, trace_id) pairs for threads whose bound contexts are
    ALL finished -- the QT703 signal (context leaked across pooled-thread
    reuse; the next request on that thread would inherit a dead trace)."""
    with REGISTRY._lock:
        items = list(REGISTRY._thread_traces.items())
    leaks = []
    for _tid, (tname, ctxs) in items:
        if ctxs and all(c.done for c in ctxs):
            leaks.append((tname, ctxs[-1].trace_id))
    return leaks


def traces() -> list:
    """Retained finished traces (JSON-ready dicts, oldest first). Treat
    as read-only; :func:`reset` drops them."""
    with REGISTRY._lock:
        return list(REGISTRY._traces)


def chrome_trace_events(trs: list) -> list:
    """Convert trace dicts (:func:`traces` / ``export_traces`` files) to
    Chrome trace-event objects: one ``ph="X"`` complete event per span
    (phase spans keep ``cat="phase"``), ``ph="s"/"f"`` flow events per
    causal link, instants for trace events, and thread-name metadata.
    Pure function -- ``tools/traceview.py --chrome`` uses it offline."""
    events = [{"ph": "M", "pid": 0, "tid": 0, "name": "process_name",
               "args": {"name": "quest_tpu"}}]
    tids: dict[str, int] = {}

    def tid_of(thread_name):
        tid = tids.get(thread_name)
        if tid is None:
            tid = tids[thread_name] = len(tids) + 1
            events.append({"ph": "M", "pid": 0, "tid": tid,
                           "name": "thread_name",
                           "args": {"name": thread_name}})
        return tid

    flow = 0
    for t in trs:
        base_us = t["t0"] * 1e6
        by_id = {sp["id"]: sp for sp in t["spans"]}
        for sp in t["spans"]:
            events.append({
                "ph": "X", "pid": 0, "tid": tid_of(sp.get("thread", "?")),
                "name": sp["name"], "cat": sp.get("cat", "span"),
                "ts": base_us + sp["t0_ms"] * 1e3,
                "dur": (sp["dur_ms"] or 0.0) * 1e3,
                "args": {"trace_id": t["trace_id"], "span_id": sp["id"],
                         "status": sp.get("status", "ok"),
                         **sp.get("labels", {})}})
        for ln in t.get("links", ()):
            a, b = by_id.get(ln["from"]), by_id.get(ln["to"])
            if a is None or b is None:
                continue
            flow += 1
            events.append({"ph": "s", "pid": 0,
                           "tid": tid_of(a.get("thread", "?")),
                           "id": flow, "name": ln["kind"], "cat": "link",
                           "ts": base_us + a["t0_ms"] * 1e3})
            events.append({"ph": "f", "bp": "e", "pid": 0,
                           "tid": tid_of(b.get("thread", "?")),
                           "id": flow, "name": ln["kind"], "cat": "link",
                           "ts": base_us + b["t0_ms"] * 1e3})
        for ev in t.get("events", ()):
            sp = by_id.get(ev.get("span"))
            events.append({
                "ph": "i", "pid": 0, "s": "t",
                "tid": tid_of((sp or {}).get("thread", "?")),
                "name": ev["name"], "cat": "event",
                "ts": base_us + ev["t_ms"] * 1e3,
                "args": {"trace_id": t["trace_id"],
                         **ev.get("fields", {})}})
    return events


def export_chrome_trace(path: str) -> int:
    """Write every retained trace as Perfetto-loadable Chrome trace-event
    JSON (``{"traceEvents": [...]}``); returns the trace count."""
    trs = traces()
    with open(path, "w") as fh:
        json.dump({"traceEvents": chrome_trace_events(trs),
                   "displayTimeUnit": "ms"}, fh)
    return len(trs)


def export_traces(path: str) -> int:
    """Write the retained traces verbatim (``{"traces": [...]}``), the
    ``tools/traceview.py`` input format; returns the trace count."""
    trs = traces()
    with open(path, "w") as fh:
        json.dump({"traces": trs}, fh)
    return len(trs)


# ---------------------------------------------------------------------------
# QUEST_TELEMETRY=0: swap the whole surface for no-op stubs at import, so a
# disabled process pays nothing beyond one module import (no allocation, no
# lock, no dict lookups -- the "zero-overhead-when-disabled" guarantee)
# ---------------------------------------------------------------------------

if not _ENV_ENABLED:  # pragma: no cover - exercised via subprocess test
    def _noop(*args, **kwargs):
        return None

    def _zero(*args, **kwargs):
        return 0.0

    def _empty_dict(*args, **kwargs):
        return {}

    def _null_span(*args, **kwargs):
        return _NULL_SPAN

    def _false(*args, **kwargs):
        return False

    def _empty_list(*args, **kwargs):
        return []

    def _empty_tuple(*args, **kwargs):
        return ()

    inc = set_gauge = observe = event = reset = _noop  # noqa: F811
    span = _null_span                                  # noqa: F811
    counter_value = counter_total = _zero              # noqa: F811
    counters = _empty_dict                             # noqa: F811

    def snapshot(prefix=None):                         # noqa: F811
        return {"counters": {}, "gauges": {}, "histograms": {}, "spans": {}}

    def export_jsonl(path, clear=False):               # noqa: F811
        return 0

    def events():                                      # noqa: F811
        return []

    # tracing rides the same master switch: a telemetry-disabled process
    # never traces, whatever QUEST_TRACE says (chrome_trace_events stays
    # live -- it is a pure converter over already-exported files)
    trace_on = _false                                                # noqa: F811
    start_trace = finish_trace = current_trace = _noop               # noqa: F811
    set_current_trace = clear_current_trace = _noop                  # noqa: F811
    trace_event_current = _noop                                      # noqa: F811
    current_traces = _empty_tuple                                    # noqa: F811
    traces = trace_thread_leaks = _empty_list                        # noqa: F811

    def trace_mode():                                  # noqa: F811
        return "off"

    @contextlib.contextmanager
    def trace_policy(mode):                            # noqa: F811
        yield

    def export_chrome_trace(path):                     # noqa: F811
        return 0

    def export_traces(path):                           # noqa: F811
        return 0
