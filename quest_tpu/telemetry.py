"""Engine flight recorder: one metrics spine for every layer.

The reference simulator ships no timers, counters, or trace hooks (SURVEY.md
section 5 -- its only introspection is reportQuregParams and the QASM log),
and until round 6 this build's own perf evidence was scattered across ad-hoc
dicts (scheduler.stats), bench-only printouts (per-pass floors) and silent
fast-path bailouts nobody could see. This module is the single registry all
of them report into and every artifact is derived from:

- **Counters / gauges / histograms**, labeled Prometheus-style
  (``inc("engine_fallback_total", reason="df_tile_mismatch")``) -- the
  fusion planner, the distributed scheduler, the exchange kernels, the
  Pallas dispatch layer and the trajectory noise engine (the
  ``trajectory_*`` series: channel sites unraveled per kind, trajectories
  run, ensembles driven -- docs/trajectories.md) all record here (see the
  instrumentation map in docs/observability.md).
- **Nested host-side spans** with monotonic timing
  (``with span("fusion.plan", qubits=26): ...``): each completed span
  aggregates into the registry (count / total_s / max_s) and, optionally,
  streams one JSONL event (``QUEST_TELEMETRY_JSONL=/path`` or
  :func:`export_jsonl`).
- **Snapshots**: :func:`snapshot` returns the whole registry as one nested
  JSON-ready dict -- ``bench.py`` embeds it in ``BENCH_DETAIL.json`` so the
  per-pass / comm-volume / fallback story ships with every headline number.

Semantics notes:

- Everything here is HOST-side accounting. Inside ``jax.jit`` the
  instrumented code runs once per *trace*, so counters count traced work
  (plan shape, comm chunk-units of the compiled program), not per-execution
  device work; span durations around jitted calls measure dispatch (plus
  compilation on the first call), not device drain.
- **Zero overhead when disabled**: ``QUEST_TELEMETRY=0`` rebinds the whole
  public surface to no-op stubs at import (a disabled process records
  nothing and allocates nothing). In-process, :func:`disabled` flips the
  same guard temporarily -- tests use it to assert bit-identical results.
- Thread-safe: one lock around the registry maps, a thread-local span
  stack, so instrumented code may run from any thread.
"""

from __future__ import annotations

import contextlib
import json
import os
import sys
import threading
import time

__all__ = [
    "enabled", "disabled", "inc", "set_gauge", "observe", "span", "event",
    "counter_value", "counter_total", "counters", "snapshot", "reset",
    "export_jsonl", "events",
]

#: import-time master switch; QUEST_TELEMETRY=0 swaps in the no-op stubs
_ENV_ENABLED = os.environ.get("QUEST_TELEMETRY", "1").strip().lower() \
    not in ("0", "false", "off")

#: if set, every completed span / event streams one JSON line here
_JSONL_ENV = "QUEST_TELEMETRY_JSONL"

#: cap on the in-memory event ring (oldest dropped first): a flight
#: recorder must never grow without bound inside a long-lived server
_MAX_EVENTS = 1 << 16


def _label_key(labels: dict) -> str:
    """Canonical ``{k=v,...}`` suffix (sorted keys; '' when unlabeled)."""
    if not labels:
        return ""
    items = ",".join(f"{k}={labels[k]}" for k in sorted(labels))
    return "{" + items + "}"


def _series_key(name: str, labels: dict) -> str:
    return name + _label_key(labels)


class _SpanHandle:
    """One live span: context manager recording a monotonic duration into
    the registry on exit (and one JSONL event). Nesting is tracked via the
    registry's thread-local stack; ``path`` is the '/'-joined ancestry."""

    __slots__ = ("_reg", "name", "labels", "_t0", "path", "duration_s")

    def __init__(self, reg: "MetricsRegistry", name: str, labels: dict):
        self._reg = reg
        self.name = name
        self.labels = labels
        self._t0 = 0.0
        self.path = name
        self.duration_s = None

    def __enter__(self):
        stack = self._reg._span_stack()
        if stack:
            self.path = stack[-1].path + "/" + self.name
        stack.append(self)
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, exc_type, exc, tb):
        self.duration_s = time.perf_counter() - self._t0
        stack = self._reg._span_stack()
        if stack and stack[-1] is self:
            stack.pop()
        self._reg._finish_span(self)
        return False


class _NullSpan:
    """Shared no-op span for the disabled path (no allocation per call)."""

    __slots__ = ()
    duration_s = None
    path = ""

    def __enter__(self):
        return self

    def __exit__(self, exc_type, exc, tb):
        return False


_NULL_SPAN = _NullSpan()


def _registry_lock():
    """The registry's lock from the instrumented sync layer
    (``resilience.sync``, name ``telemetry.registry``, ``record=False``
    so recording a metric never records a metric). Telemetry sits below
    everything, so the layer is probed via sys.modules instead of
    imported: at bootstrap (sync itself imports telemetry first) this
    falls back to a raw lock, which sync adopts at ITS import."""
    sync = sys.modules.get(__name__.rsplit(".", 1)[0] + ".resilience.sync")
    if sync is not None:
        return sync.Lock("telemetry.registry", record=False)
    return threading.Lock()  # concheck: allow-raw-lock (bootstrap only)


class MetricsRegistry:
    """Process-global metric store; all module-level helpers delegate to
    one shared instance (:data:`REGISTRY`)."""

    def __init__(self):
        self._lock = _registry_lock()
        self._local = threading.local()
        self.enabled = _ENV_ENABLED
        self._jsonl_fh = None
        self._jsonl_path = os.environ.get(_JSONL_ENV)
        self._reset_locked()

    # -- storage ------------------------------------------------------------

    def _reset_locked(self):
        self._counters: dict[str, float] = {}
        self._gauges: dict[str, float] = {}
        self._hists: dict[str, dict] = {}
        self._spans: dict[str, dict] = {}
        self._events: list[dict] = []

    def reset(self) -> None:
        """Drop every recorded metric and event (tests, bench sections)."""
        with self._lock:
            self._reset_locked()

    def _span_stack(self) -> list:
        stack = getattr(self._local, "stack", None)
        if stack is None:
            stack = self._local.stack = []
        return stack

    # -- recording ----------------------------------------------------------

    def inc(self, name: str, value: float = 1.0, **labels) -> None:
        """Add ``value`` to the counter series ``name{labels}``."""
        if not self.enabled:
            return
        key = _series_key(name, labels)
        with self._lock:
            self._counters[key] = self._counters.get(key, 0.0) + value

    def set_gauge(self, name: str, value: float, **labels) -> None:
        """Set the gauge series ``name{labels}`` to ``value``."""
        if not self.enabled:
            return
        key = _series_key(name, labels)
        with self._lock:
            self._gauges[key] = float(value)

    def observe(self, name: str, value: float, **labels) -> None:
        """Record one observation into the histogram ``name{labels}``
        (count / sum / min / max aggregate -- enough to derive rates and
        spot outliers without shipping raw samples)."""
        if not self.enabled:
            return
        key = _series_key(name, labels)
        v = float(value)
        with self._lock:
            h = self._hists.get(key)
            if h is None:
                self._hists[key] = {"count": 1, "sum": v, "min": v, "max": v}
            else:
                h["count"] += 1
                h["sum"] += v
                h["min"] = min(h["min"], v)
                h["max"] = max(h["max"], v)

    def span(self, name: str, **labels):
        """Context manager timing a nested host-side region."""
        if not self.enabled:
            return _NULL_SPAN
        return _SpanHandle(self, name, labels)

    def event(self, name: str, **fields) -> None:
        """Append one raw flight-recorder event (JSONL-exportable)."""
        if not self.enabled:
            return
        self._append_event({"kind": "event", "name": name, "t": time.time(),
                            **fields})

    def _finish_span(self, sp: _SpanHandle) -> None:
        key = _series_key(sp.name, sp.labels)
        with self._lock:
            agg = self._spans.get(key)
            if agg is None:
                self._spans[key] = {"count": 1, "total_s": sp.duration_s,
                                    "max_s": sp.duration_s}
            else:
                agg["count"] += 1
                agg["total_s"] += sp.duration_s
                agg["max_s"] = max(agg["max_s"], sp.duration_s)
        self._append_event({"kind": "span", "name": sp.name, "t": time.time(),
                            "path": sp.path, "dur_s": round(sp.duration_s, 9),
                            **({"labels": sp.labels} if sp.labels else {})})

    def _append_event(self, ev: dict) -> None:
        with self._lock:
            self._events.append(ev)
            if len(self._events) > _MAX_EVENTS:
                del self._events[: len(self._events) - _MAX_EVENTS]
        path = self._jsonl_path
        if path:
            self._stream_jsonl(ev, path)

    def _stream_jsonl(self, ev: dict, path: str) -> None:
        try:
            if self._jsonl_fh is None:
                self._jsonl_fh = open(path, "a", buffering=1)
            self._jsonl_fh.write(json.dumps(ev) + "\n")
        except OSError:  # a broken sink must never take the engine down
            self._jsonl_path = None

    # -- reading ------------------------------------------------------------

    def counter_value(self, name: str, **labels) -> float:
        """Value of one exact counter series (0.0 if never incremented)."""
        with self._lock:
            return self._counters.get(_series_key(name, labels), 0.0)

    def counter_total(self, name: str) -> float:
        """Sum of a counter across ALL label series."""
        prefix = name + "{"
        with self._lock:
            return sum(v for k, v in self._counters.items()
                       if k == name or k.startswith(prefix))

    def counters(self, name: str) -> dict:
        """{label-suffix: value} for every series of ``name`` ('' when
        unlabeled) -- the per-reason breakdown tests assert against."""
        prefix = name + "{"
        out = {}
        with self._lock:
            for k, v in self._counters.items():
                if k == name:
                    out[""] = v
                elif k.startswith(prefix):
                    out[k[len(name):]] = v
        return out

    def snapshot(self, prefix: str | None = None) -> dict:
        """The whole registry as one JSON-ready dict; ``prefix`` filters
        series names. Histogram/span sums are rounded to keep artifacts
        compact and diff-stable."""
        def keep(k):
            return prefix is None or k.startswith(prefix)

        def num(v):
            return int(v) if float(v).is_integer() else round(v, 6)

        with self._lock:
            return {
                "counters": {k: num(v)
                             for k, v in sorted(self._counters.items())
                             if keep(k)},
                "gauges": {k: round(v, 6)
                           for k, v in sorted(self._gauges.items())
                           if keep(k)},
                "histograms": {
                    k: {"count": h["count"], "sum": round(h["sum"], 6),
                        "min": round(h["min"], 6), "max": round(h["max"], 6)}
                    for k, h in sorted(self._hists.items()) if keep(k)},
                "spans": {
                    k: {"count": a["count"],
                        "total_s": round(a["total_s"], 6),
                        "max_s": round(a["max_s"], 6)}
                    for k, a in sorted(self._spans.items()) if keep(k)},
            }

    def events(self) -> list:
        """A copy of the in-memory event ring (most recent last)."""
        with self._lock:
            return list(self._events)

    def export_jsonl(self, path: str, clear: bool = False) -> int:
        """Write every buffered event as one JSON line each; returns the
        number written. ``clear`` drops the buffer afterwards."""
        with self._lock:
            evs = list(self._events)
            if clear:
                self._events = []
        with open(path, "w") as fh:
            for ev in evs:
                fh.write(json.dumps(ev) + "\n")
        return len(evs)


#: the process-global registry every instrumented layer reports into
REGISTRY = MetricsRegistry()


# ---------------------------------------------------------------------------
# module-level convenience surface (what instrumented code imports)
# ---------------------------------------------------------------------------

def enabled() -> bool:
    """True when telemetry is recording (QUEST_TELEMETRY != 0 and not
    inside a :func:`disabled` block)."""
    return REGISTRY.enabled


@contextlib.contextmanager
def disabled():
    """Temporarily disable all recording in-process (tests use this to
    assert the instrumented paths are result-identical without telemetry;
    for true zero-overhead use QUEST_TELEMETRY=0 at process start)."""
    prev = REGISTRY.enabled
    REGISTRY.enabled = False
    try:
        yield
    finally:
        REGISTRY.enabled = prev


def inc(name: str, value: float = 1.0, **labels) -> None:
    REGISTRY.inc(name, value, **labels)


def set_gauge(name: str, value: float, **labels) -> None:
    REGISTRY.set_gauge(name, value, **labels)


def observe(name: str, value: float, **labels) -> None:
    REGISTRY.observe(name, value, **labels)


def span(name: str, **labels):
    return REGISTRY.span(name, **labels)


def event(name: str, **fields) -> None:
    REGISTRY.event(name, **fields)


def counter_value(name: str, **labels) -> float:
    return REGISTRY.counter_value(name, **labels)


def counter_total(name: str) -> float:
    return REGISTRY.counter_total(name)


def counters(name: str) -> dict:
    return REGISTRY.counters(name)


def snapshot(prefix: str | None = None) -> dict:
    return REGISTRY.snapshot(prefix)


def reset() -> None:
    REGISTRY.reset()


def export_jsonl(path: str, clear: bool = False) -> int:
    return REGISTRY.export_jsonl(path, clear)


def events() -> list:
    return REGISTRY.events()


# ---------------------------------------------------------------------------
# QUEST_TELEMETRY=0: swap the whole surface for no-op stubs at import, so a
# disabled process pays nothing beyond one module import (no allocation, no
# lock, no dict lookups -- the "zero-overhead-when-disabled" guarantee)
# ---------------------------------------------------------------------------

if not _ENV_ENABLED:  # pragma: no cover - exercised via subprocess test
    def _noop(*args, **kwargs):
        return None

    def _zero(*args, **kwargs):
        return 0.0

    def _empty_dict(*args, **kwargs):
        return {}

    def _null_span(*args, **kwargs):
        return _NULL_SPAN

    inc = set_gauge = observe = event = reset = _noop  # noqa: F811
    span = _null_span                                  # noqa: F811
    counter_value = counter_total = _zero              # noqa: F811
    counters = _empty_dict                             # noqa: F811

    def snapshot(prefix=None):                         # noqa: F811
        return {"counters": {}, "gauges": {}, "histograms": {}, "spans": {}}

    def export_jsonl(path, clear=False):               # noqa: F811
        return 0

    def events():                                      # noqa: F811
        return []
