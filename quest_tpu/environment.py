"""Execution environment (reference: ``QuESTEnv``, QuEST.h:405-415).

The reference's env carries (rank, numRanks, seeds) and is created once per
process around MPI_Init / GPU probing (QuEST_cpu_distributed.c:131-164,
QuEST_cuQuantum.cu:147-204). The TPU-native env instead carries:

  - a ``jax.sharding.Mesh`` over the visible devices (1-D axis ``"amps"``),
    the analogue of the MPI communicator. The reference requires a power-of-2
    rank count (QuEST_validation.c:354-366); we validate the same so the shard
    axis always aligns with the top qubits.
  - the seed state: a list of user seeds plus a host-side Mersenne-Twister
    generator (numpy's MT19937 -- same algorithm as the reference's
    mt19937ar.c) used for measurement outcomes. Because there is a single
    controller process, cross-rank seed agreement
    (QuEST_cpu_distributed.c:1400-1418) is automatic.

Unlike the reference, distribution and acceleration compose: the same env
drives 1 chip or a pod slice.
"""

from __future__ import annotations

import os
import time
from dataclasses import dataclass, field
from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec

from . import validation

#: name of the mesh axis amplitudes are sharded over
AMP_AXIS = "amps"


@dataclass
class QuESTEnv:
    mesh: Optional[Mesh]
    seeds: list[int] = field(default_factory=list)
    rng: np.random.RandomState = None
    #: pod-slice count of the device set (1 = single slice). Devices are
    #: ordered slice-major, so the chip axis forms the LOW shard bits (hot
    #: relocation targets ride ICI) and only the top log2(num_slices)
    #: sharded qubits cross DCN; parallel.mesh.shard_bit_link classifies.
    num_slices: int = 1

    # kept for reference API parity (reportQuESTEnv prints them)
    @property
    def num_ranks(self) -> int:
        return self.mesh.size if self.mesh is not None else 1

    @property
    def rank(self) -> int:
        return 0  # single-controller SPMD: there is one logical process

    @property
    def requires_sharding(self) -> bool:
        """True when registers MUST shard over the mesh: multi-host
        (jax.distributed) execution, where every process owns devices and a
        replicated-on-one-device fallback is impossible. Single-host meshes
        replicate registers too small to split instead of rejecting them
        (more permissive than the reference's >=1-amp-per-node rule,
        QuEST_validation.c:368-377, which applies here only multi-host)."""
        return jax.process_count() > 1

    def sharding(self, num_amps: int) -> Optional[NamedSharding]:
        """Block-partition a planar (2, num_amps) amplitude array over the
        mesh (the top log2(numDevices) qubits), as statevec_createQureg's
        chunking (QuEST_cpu.c:1296-1319). Falls back to None (single device /
        too few amps to split)."""
        if self.mesh is None or self.mesh.size == 1 or num_amps < self.mesh.size:
            return None
        return NamedSharding(self.mesh, PartitionSpec(None, AMP_AXIS))

    def replicated(self) -> Optional[NamedSharding]:
        if self.mesh is None:
            return None
        return NamedSharding(self.mesh, PartitionSpec())


def createQuESTEnv(devices: Sequence[jax.Device] | None = None,
                   num_slices: int | None = None) -> QuESTEnv:
    """Create the environment (createQuESTEnv, QuEST.h:2196).

    ``devices`` defaults to all visible devices; a power-of-2 count is
    required (same constraint as the reference's validateNumRanks).
    ``num_slices`` declares a multi-slice (DCN-connected) topology: devices
    are ordered slice-major so intra-slice chips form the minor shard bits
    (hot qubits ride ICI; see parallel.mesh). Auto-detected from the TPU
    runtime's ``slice_index`` attribute when omitted.
    """
    func = "createQuESTEnv"
    if devices is None:
        devices = jax.devices()
        # trim to the largest power of two, like users launching 2^k ranks
        count = 1 << (len(devices).bit_length() - 1)
        devices = devices[:count]
    validation.validate_num_ranks(len(devices), func)
    explicit_slices = num_slices is not None
    if num_slices is None:
        num_slices = len({getattr(d, "slice_index", 0) for d in devices})
    bad = (num_slices < 1 or len(devices) % num_slices
           or num_slices & (num_slices - 1))
    if bad:
        if explicit_slices:
            raise validation.QuESTError(
                f"num_slices={num_slices} does not evenly split "
                f"{len(devices)} devices into power-of-2 slices")
        num_slices = 1  # auto-detect is stats-only; never reject hardware
    if num_slices > 1:
        # slice-major order (chip axis = minor shard bits -> hot qubits
        # ride ICI), stable within a slice to preserve the caller's order
        devices = sorted(devices, key=lambda d: getattr(d, "slice_index", 0))
    mesh = Mesh(np.asarray(devices), (AMP_AXIS,))
    env = QuESTEnv(mesh=mesh, num_slices=num_slices)
    seedQuESTDefault(env)
    return env


def destroyQuESTEnv(env: QuESTEnv) -> None:
    """No-op (no MPI_Finalize needed); kept for API parity."""


def syncQuESTEnv(env: QuESTEnv) -> None:
    """Barrier analogue: block until enqueued device work is done
    (reference: MPI_Barrier, QuEST_cpu_distributed.c:166-168)."""
    (jax.device_put(0) + 0).block_until_ready()


def syncQuESTSuccess(success_code: int) -> int:
    """All-ranks success agreement (MPI_LAND allreduce in the reference,
    QuEST_cpu_distributed.c:170-174). Single controller: identity."""
    return success_code


def reportQuESTEnv(env: QuESTEnv) -> None:
    """Print deployment info (reportQuESTEnv; format follows
    getEnvironmentString, QuEST_cpu_distributed.c:185-208)."""
    print("EXECUTION ENVIRONMENT:")
    print(f"Backend: TPU-native (JAX/XLA {jax.__version__})")
    print(f"Number of devices: {env.num_ranks}")
    plats = {d.platform for d in (env.mesh.devices.flat if env.mesh is not None else [])}
    print(f"Device platform(s): {', '.join(sorted(plats)) or 'none'}")
    print(f"Precision default: {os.environ.get('QUEST_PRECISION', '1')}")


def getEnvironmentString(env: QuESTEnv) -> str:
    """Fill ``env_str`` with the execution-environment summary (QuEST.h:123)."""
    n = env.num_ranks
    return f"CUDA=0 OpenMP=0 MPI=0 TPU=1 threads=1 ranks={n} devices={n}"


# ---------------------------------------------------------------------------
# seeding (reference: seedQuEST/seedQuESTDefault/getQuESTSeeds,
# QuEST_common.c:195-217 + mt19937ar.c)
# ---------------------------------------------------------------------------

def seedQuEST(env: QuESTEnv, seeds: Sequence[int]) -> None:
    """Seed the measurement RNG from a user key array. numpy's MT19937 seeds
    arrays via init_by_array -- the same routine the reference feeds
    (QuEST_common.c:209-217)."""
    validation.validate_num_seeds(seeds, "seedQuEST")
    env.seeds = [int(s) for s in seeds]
    env.rng = np.random.RandomState(np.asarray(env.seeds, dtype=np.uint32))


def seedQuESTDefault(env: QuESTEnv) -> None:
    """Default seeding from time + pid (QuEST_common.c:195-207)."""
    seedQuEST(env, [int(time.time()) & 0xFFFFFFFF, os.getpid() & 0xFFFFFFFF])


def getQuESTSeeds(env: QuESTEnv) -> list[int]:
    """The seeds the env's RNG was last seeded with (QuEST.h:126)."""
    return list(env.seeds)
