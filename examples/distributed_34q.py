"""BASELINE.json config 5: a distributed state-vector sharded over a mesh.

The reference scales Hilbert space with MPI amplitude sharding
(QuEST_cpu_distributed.c: exchangeStateVectors pair swaps); here the same
partition is a `jax.sharding.Mesh` over all visible devices, and XLA emits
the collective_permute / all-to-all traffic when a gate touches a sharded
(top) qubit.

At the target scale -- 34 qubits on a v5p-16 pod slice (128 GiB of
amplitudes across 16 chips) -- run this unchanged on the pod:

    python examples/distributed_34q.py --qubits 34

On smaller hardware it auto-scales the register to fit (the sharding logic
is identical; only numAmpsPerChunk changes, exactly as with mpirun -np).
Emulate the 16-way mesh on CPU with:

    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=16 \
        python examples/distributed_34q.py --qubits 20
"""

import argparse
import time

import _bootstrap  # noqa: F401  (repo path + QUEST_PLATFORM handling)

import jax
import numpy as np


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--qubits", type=int, default=None,
                   help="default: largest register that fits in ~60%% of HBM")
    p.add_argument("--depth", type=int, default=4)
    args = p.parse_args()

    import quest_tpu as qt
    from quest_tpu.circuits import Circuit

    devices = jax.devices()
    env = qt.createQuESTEnv(devices)
    print(f"mesh: {len(devices)} x {devices[0].device_kind}")

    n = args.qubits
    if n is None:
        stats = devices[0].memory_stats() or {}
        per_dev = stats.get("bytes_limit", 16 << 30) * 0.6
        total = per_dev * len(devices)
        n = int(np.log2(total / 8))  # planar f32: 8 bytes/amp
        print(f"auto-sized to {n} qubits")

    qureg = qt.createQureg(n, env)
    qt.initPlusState(qureg)
    shards = len(qureg.amps.sharding.device_set) if qureg.amps.sharding else 1
    print(f"{n}-qubit register: {qureg.num_amps_total:,} amps over "
          f"{shards} shard(s)")

    # random layers touching both local and sharded (top) qubits: gates on
    # the top log2(ndev) qubits compile to cross-device collectives
    circ = Circuit(n)
    rng = np.random.RandomState(7)
    for layer in range(args.depth):
        for q in range(n):
            (circ.hadamard if rng.rand() < 0.5 else
             lambda q: circ.rotateZ(q, rng.rand()))(q)
        for q in range(layer % 2, n - 1, 2):
            circ.controlledNot(q, q + 1)
        circ.controlledPhaseFlip(0, n - 1)

    # two-frame Pallas planning sized for the shard-local state: fused runs
    # execute per shard under shard_map (sharded-qubit controls/diagonals
    # resolve against the shard index in-kernel); gates no frame localises
    # fall back to the sharding-aware engine automatically
    use_pallas = jax.default_backend() == "tpu"
    fused = circ.fused(max_qubits=5, pallas=use_pallas,
                       shard_devices=shards if use_pallas else None)

    # compiled_blocks bypasses Circuit.run, so build it under the execution
    # mesh (the block executables pin the ambient contexts at build time)
    from quest_tpu import fusion as _fusion
    from quest_tpu.circuits import _register_mesh

    with _fusion.pallas_mesh(_register_mesh(qureg)):
        fn = fused.compiled_blocks(max_gates=24, donate=True)

    t0 = time.time()
    amps = fn(qureg.amps)
    amps.block_until_ready()
    print(f"compile+first step: {time.time() - t0:.1f}s")

    t0 = time.time()
    amps = fn(amps)
    qureg.put(amps)
    prob = qt.calcTotalProb(qureg)
    dt = time.time() - t0
    print(f"step: {dt:.3f}s  ({len(circ)} gates, {len(circ)/dt:.1f} gates/s)")
    print(f"total probability: {prob:.6f}")
    assert abs(prob - 1.0) < 1e-4


if __name__ == "__main__":
    main()
