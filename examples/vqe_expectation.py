"""VQE-style workflow: ansatz circuit + PauliHamil expectation values.

Exercises the operators/calculations layer end-to-end: a hardware-efficient
ansatz evolves a register, and a transverse-field Ising Hamiltonian
H = -J sum Z_i Z_{i+1} - h sum X_i is evaluated with calcExpecPauliHamil --
which this framework lowers to ONE fused XLA program for the whole Pauli
sum (the reference clones the state and reduces once per term,
QuEST_common.c:505-532).
"""

import time

import _bootstrap  # noqa: F401  (repo path + QUEST_PLATFORM handling)

import numpy as np

import quest_tpu as qt


def build_hamiltonian(n: int, j: float, h: float) -> "qt.PauliHamil":
    terms = []
    coeffs = []
    for q in range(n - 1):
        codes = [0] * n
        codes[q] = codes[q + 1] = 3          # Z Z
        terms.append(codes)
        coeffs.append(-j)
    for q in range(n):
        codes = [0] * n
        codes[q] = 1                          # X
        terms.append(codes)
        coeffs.append(-h)
    hamil = qt.createPauliHamil(n, len(coeffs))
    qt.initPauliHamil(hamil, coeffs, [c for row in terms for c in row])
    return hamil


def ansatz(n: int, params: np.ndarray) -> "qt.Circuit":
    circ = qt.Circuit(n)
    k = 0
    for layer in range(params.shape[0]):
        for q in range(n):
            circ.rotateY(q, float(params[layer, q, 0]))
            circ.rotateZ(q, float(params[layer, q, 1]))
        for q in range(layer % 2, n - 1, 2):
            circ.controlledNot(q, q + 1)
    return circ


def main():
    n, layers = 12, 4
    rng = np.random.RandomState(11)
    params = rng.uniform(0, 2 * np.pi, size=(layers, n, 2))

    env = qt.createQuESTEnv()
    hamil = build_hamiltonian(n, j=1.0, h=0.7)
    qureg = qt.createQureg(n, env)
    work = qt.createQureg(n, env)

    qt.initZeroState(qureg)
    circ = ansatz(n, params).fused(max_qubits=5, pallas=True)
    t0 = time.time()
    circ.run(qureg)
    e = qt.calcExpecPauliHamil(qureg, hamil, work)
    print(f"<H> = {e:.6f}   ({time.time() - t0:.2f}s incl. compile)")

    # parameter-shift style sweep (each parameter set bakes new fused
    # matrices, so evaluations retrace; the persistent compile cache and
    # structural reuse keep this to ~2s per energy on the tunnelled chip)
    t0 = time.time()
    energies = []
    for delta in (0.0, 0.1, 0.2):
        p2 = params.copy()
        p2[0, 0, 0] += delta
        qt.initZeroState(qureg)
        ansatz(n, p2).fused(max_qubits=5, pallas=True).run(qureg)
        energies.append(qt.calcExpecPauliHamil(qureg, hamil, work))
    print(f"energy sweep {['%.4f' % x for x in energies]} "
          f"({time.time() - t0:.2f}s for 3 evaluations)")

    # sanity: ground-state energy of the 4-qubit version vs exact dense H
    n4 = 4
    h4 = build_hamiltonian(n4, 1.0, 0.7)
    q4 = qt.createQureg(n4, env)
    w4 = qt.createQureg(n4, env)
    qt.initPlusState(q4)
    e4 = qt.calcExpecPauliHamil(q4, h4, w4)
    # |+...+> gives <ZZ>=0 and <X>=1 exactly: E = -h*n
    assert abs(e4 - (-0.7 * n4)) < 1e-4, e4
    print(f"4q |+> check: <H> = {e4:.6f} == -h*n = {-0.7 * n4}")


if __name__ == "__main__":
    main()
