"""Tutorial: a basic 3-qubit circuit (port of the reference's
examples/tutorial_example.c behaviour to the quest_tpu Python API)."""

import numpy as np

import _bootstrap  # noqa: F401  (repo path + QUEST_PLATFORM handling)

import quest_tpu as qt

env = qt.createQuESTEnv()

print("-" * 55)
print("Running quest_tpu tutorial:\n\t Basic circuit involving a system of 3 qubits.")
print("-" * 55)

qubits = qt.createQureg(3, env)
qt.initZeroState(qubits)

print("\nThis is our environment:")
qt.reportQuregParams(qubits)
qt.reportQuESTEnv(env)

# apply circuit
qt.hadamard(qubits, 0)
qt.controlledNot(qubits, 0, 1)
qt.rotateY(qubits, 2, 0.1)

qt.multiControlledPhaseFlip(qubits, [0, 1, 2])

u = np.array([[0.5 + 0.5j, 0.5 - 0.5j],
              [0.5 - 0.5j, 0.5 + 0.5j]])
qt.unitary(qubits, 0, u)

a, b = 0.5 + 0.5j, 0.5 - 0.5j
qt.compactUnitary(qubits, 1, a, b)

qt.rotateAroundAxis(qubits, 2, 3.14 / 2, qt.Vector(1, 0, 0))

qt.controlledCompactUnitary(qubits, 0, 1, a, b)

qt.multiControlledUnitary(qubits, [0, 1], 2, u)

toff = np.eye(8)
toff[6, 6] = toff[7, 7] = 0
toff[6, 7] = toff[7, 6] = 1
qt.multiQubitUnitary(qubits, [0, 1, 2], toff)

# study the output
print("\nCircuit output:")
prob = qt.getProbAmp(qubits, 7)
print(f"Probability amplitude of |111>: {prob}")

prob = qt.calcProbOfOutcome(qubits, 2, 1)
print(f"Probability of qubit 2 being in state 1: {prob}")

outcome = qt.measure(qubits, 0)
print(f"Qubit 0 was measured in state {outcome}")

outcome, prob = qt.measureWithStats(qubits, 2)
print(f"Qubit 2 collapsed to {outcome} with probability {prob}")

qt.destroyQureg(qubits, env)
qt.destroyQuESTEnv(env)
