"""Grover's unstructured search with X, H and multi-controlled Z only
(behavioural port of the reference's examples/grovers_search.c, at the
BASELINE.json milestone size of 12 qubits).

TPU-native twist: each Grover iteration (oracle + diffuser) is recorded once
on a :class:`quest_tpu.Circuit` and compiled to a single fused XLA program,
then reused for every repetition — instead of the reference's one kernel
launch per gate.
"""

import math
import random
import time

import _bootstrap  # noqa: F401  (repo path + QUEST_PLATFORM handling)

import quest_tpu as qt


def record_oracle(circ: qt.Circuit, num_qubits: int, sol_elem: int) -> None:
    """|solElem> -> -|solElem| via X-conjugated multi-controlled phase flip."""
    flips = [q for q in range(num_qubits) if not (sol_elem >> q) & 1]
    if flips:
        circ.multiQubitNot(flips)
    circ.multiControlledPhaseFlip(list(range(num_qubits)))
    if flips:
        circ.multiQubitNot(flips)


def record_diffuser(circ: qt.Circuit, num_qubits: int) -> None:
    """2|+><+| - I, in the Hadamard basis."""
    for q in range(num_qubits):
        circ.hadamard(q)
    circ.multiQubitNot(list(range(num_qubits)))
    circ.multiControlledPhaseFlip(list(range(num_qubits)))
    circ.multiQubitNot(list(range(num_qubits)))
    for q in range(num_qubits):
        circ.hadamard(q)


def main(num_qubits: int = 12) -> None:
    env = qt.createQuESTEnv()
    num_elems = 2 ** num_qubits
    num_reps = math.ceil(math.pi / 4 * math.sqrt(num_elems))
    print(f"numQubits: {num_qubits}, numElems: {num_elems}, numReps: {num_reps}")

    random.seed(time.time())
    sol_elem = random.randrange(num_elems)

    qureg = qt.createQureg(num_qubits, env)
    qt.initPlusState(qureg)

    iteration = qt.Circuit(num_qubits)
    record_oracle(iteration, num_qubits, sol_elem)
    record_diffuser(iteration, num_qubits)

    for _ in range(num_reps):
        iteration.run(qureg)
        print(f"prob of solution |{sol_elem}> = {qt.getProbAmp(qureg, sol_elem):.8f}")

    assert qt.getProbAmp(qureg, sol_elem) > 0.99
    qt.destroyQureg(qureg, env)
    qt.destroyQuESTEnv(env)


if __name__ == "__main__":
    main()
