"""Shared example bootstrap: make the repo importable and honour
QUEST_PLATFORM (e.g. ``QUEST_PLATFORM=cpu``) before jax initialises — the
axon TPU plugin otherwise pins JAX_PLATFORMS at interpreter start."""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), os.pardir))

if os.environ.get("QUEST_PLATFORM"):
    import jax

    jax.config.update("jax_platforms", os.environ["QUEST_PLATFORM"])
