"""Bernstein–Vazirani: recover a secret bitstring with one oracle query
(behavioural port of the reference's examples/bernstein_vazirani_circuit.c).

Qubit 0 is the ancilla; qubits 1..n-1 hold the query register.
"""

import random
import time

import _bootstrap  # noqa: F401  (repo path + QUEST_PLATFORM handling)

import quest_tpu as qt


def apply_oracle(qureg, num_qubits: int, secret: int) -> None:
    bits = secret
    for q in range(1, num_qubits):
        if bits % 2:
            qt.controlledNot(qureg, q, 0)
        bits //= 2


def main(num_qubits: int = 15) -> None:
    env = qt.createQuESTEnv()
    random.seed(time.time())
    secret = random.randrange(2 ** (num_qubits - 1))

    qureg = qt.createQureg(num_qubits, env)
    qt.initZeroState(qureg)

    # prepare ancilla in |-> and query register in |+>
    qt.pauliX(qureg, 0)
    for q in range(num_qubits):
        qt.hadamard(qureg, q)

    apply_oracle(qureg, num_qubits, secret)

    for q in range(num_qubits):
        qt.hadamard(qureg, q)

    # state is now |secret>|1>
    ind = 2 * secret + 1
    prob = qt.getProbAmp(qureg, ind)
    print(f"success probability: {prob:.10f}")
    assert prob > 0.99

    qt.destroyQureg(qureg, env)
    qt.destroyQuESTEnv(env)


if __name__ == "__main__":
    main()
